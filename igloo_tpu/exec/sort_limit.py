"""Sort / Limit / Offset kernels.

The reference delegates ORDER BY/LIMIT to DataFusion entirely (no custom operator).
TPU design: multi-key sort = k iterated stable argsorts over order-normalized int64
lanes (kernels.lex_argsort) — no comparators, fully static shapes. LIMIT is a mask
over the running live-row count, not a truncation, so shapes stay put.
"""
from __future__ import annotations

import jax.numpy as jnp

from igloo_tpu.exec import kernels as K
from igloo_tpu.exec.batch import DeviceBatch
from igloo_tpu.exec.expr_compile import Compiled, Env


def sort_batch(batch: DeviceBatch, keys: list[Compiled], ascending: list[bool],
               nulls_first: list[bool], consts: tuple = ()) -> DeviceBatch:
    """Jit-traceable stable sort; dead rows end up last."""
    env = Env.from_batch(batch, consts)
    lanes = []
    for k, asc, nf in zip(keys, ascending, nulls_first):
        v, nl = k.fn(env)
        lanes.extend(K.sort_lanes_for(v, nl, k.dtype.is_float, asc, nf))
    perm = K.lex_argsort(lanes, batch.live)
    return K.apply_perm(batch, perm)


def limit_batch(batch: DeviceBatch, limit, offset: int = 0) -> DeviceBatch:
    """Jit-traceable: keep live rows (offset, offset+limit] in current row order."""
    cum = jnp.cumsum(batch.live.astype(jnp.int64))
    keep = batch.live & (cum > offset)
    if limit is not None:
        keep = keep & (cum <= offset + limit)
    return DeviceBatch(batch.schema, batch.columns, keep)
