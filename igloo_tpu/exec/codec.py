"""Lossless narrow-transfer codec for the host->HBM boundary.

The engine computes on int64/float64 lanes (SQL semantics), but shipping those
lanes verbatim wastes the scarcest resource on a tunneled TPU: host<->device
bandwidth (measured ~10-20 MB/s through the axon tunnel, flat per byte — see
BASELINE.md). A 6M-row float64 column is 48 MB on the wire even when every
value is a whole number under 50.

This codec picks, per column and on the host, the smallest *provably lossless*
carrier representation and uploads that. Since PR 16 the carrier is also the
RESIDENT representation: the narrow array stays in HBM as
`DeviceColumn.values` with its `WidenSpec` attached, and operators widen
in-jit at the point of use (`batch.wide_values`; XLA fuses the cast/divide
into the consumer) — so HBM footprint, exchange, and spill all pay carrier
bytes, and full lanes exist only transiently inside fused programs and at the
Arrow output boundary (docs/compressed_execution.md). Carriers, tried
narrowest-first:

- integer family (int64/int32/date32/timestamp lanes): offset shrink —
  ``carrier = v - off`` cast to int8/int16/int32 when the value RANGE fits;
  widen = ``carrier.astype(lane) + off``. Exact by construction.
- float lanes: scaled-decimal shrink — ``c = rint(v * scale)`` for scale in
  {1, 100, 10000} when c fits int32 AND ``c / scale == v`` elementwise on the
  host (float64 division, verified value by value); widen =
  ``c.astype(f64) / scale``. TPC-H prices/discounts/taxes are decimals with
  <= 4 fractional digits, so they ride int8/int16/int32 carriers. IEEE-754
  division is deterministic, so the host check guarantees the device result
  bit-for-bit (the TPU's emulated f64 divide is IEEE-correct; verified by
  tests/test_codec.py on CPU and by the bench harness on device).
- float64 -> float32 round-trip: when ``v == f32(v)`` exactly (NaN-aware).
- everything else ships as the lane dtype unchanged.

The reference engine has no analog (it streams Arrow RecordBatches in-process,
reference crates/engine/src/operators/parquet_scan.rs:40-85); this boundary
exists only because the TPU sits across an interconnect.
"""
from __future__ import annotations

import functools
import os
import threading
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def encoded_enabled() -> bool:
    """Master kill switch for compressed execution (docs/compressed_execution.md).

    `IGLOO_TPU_ENCODED=0` disables EVERY narrowing layer — uploads ship full
    engine lanes, columns are never carrier-resident in HBM, exchange/GRACE
    buffers stay decoded — which is what makes it the bit-identical A/B
    baseline for the byte counters (`codec.*`, `exchange.bytes`, `grace.*`).
    Read per call so tests/smokes can flip it between queries."""
    return os.environ.get("IGLOO_TPU_ENCODED", "1") != "0"


def rle_enabled() -> bool:
    """Run-length transfer carrier for sorted/clustered columns
    (`IGLOO_TPU_RLE=0` to disable; subordinate to IGLOO_TPU_ENCODED)."""
    return encoded_enabled() and os.environ.get("IGLOO_TPU_RLE", "1") != "0"


_I8 = (-(1 << 7), (1 << 7) - 1)
_I16 = (-(1 << 15), (1 << 15) - 1)
_I32 = (-(1 << 31), (1 << 31) - 1)
_INT_STEPS = ((np.int8, _I8), (np.int16, _I16), (np.int32, _I32))


@dataclass(frozen=True)
class WidenSpec:
    """How to reconstruct the engine lane from the carrier, on device.

    lane:   target numpy dtype name ('int64', 'float64', ...)
    offset: integer added after the cast (int paths; 0 for float paths)
    scale:  divisor applied after the cast (float paths; 1 = none)
    """
    lane: str
    offset: int = 0
    scale: float = 1.0

    def widen(self, a: jax.Array, scale_arg=None, offset_arg=None) -> jax.Array:
        """`scale_arg`/`offset_arg`, when given, must be RUNTIME 0-d arrays
        holding self.scale/self.offset. Scale: baking the divisor in as a
        constant lets XLA rewrite the divide into a multiply by the (inexact)
        reciprocal, which breaks the host-verified exactness for ~13% of
        scaled-decimal values. Offset: it is data-dependent (the column min),
        so baking it in would compile a fresh widen program per distinct min
        (one per chunk in the chunked executor)."""
        lane = jnp.dtype(self.lane)
        if self.scale != 1.0:
            s = (scale_arg.astype(lane) if scale_arg is not None
                 else lane.type(self.scale))
            return a.astype(lane) / s
        if self.offset:
            off = (offset_arg.astype(lane) if offset_arg is not None
                   else lane.type(self.offset))
            return a.astype(lane) + off
        if a.dtype != lane:
            return a.astype(lane)
        return a

    def key(self) -> tuple:
        """Static jit-cache key: everything EXCEPT the data-dependent payload
        values (offset rides in at runtime; only its presence is static)."""
        return (self.lane, self.scale != 1.0, self.scale, bool(self.offset))


def _shrink_int(v: np.ndarray, lane: np.dtype):
    """Offset-shrink an integer array; None when it cannot shrink."""
    if v.size == 0:
        return v.astype(np.int8), WidenSpec(lane.name)
    lo, hi = int(v.min()), int(v.max())
    for nd, (nlo, nhi) in _INT_STEPS:
        nd_ = np.dtype(nd)
        if nd_.itemsize >= lane.itemsize:
            return None
        span = hi - lo
        if span <= nhi - nlo:
            # center the carrier range when an offset is needed at all
            off = 0 if (nlo <= lo and hi <= nhi) else lo - nlo
            return (v - off).astype(nd), WidenSpec(lane.name, offset=off)
    return None


_FLOAT_SCALES = (1.0, 100.0, 10000.0)

# one-time on-device canary for the scaled-decimal path: None = not yet run.
# The host verifies ``c / scale == v`` in IEEE f64, but the device replays the
# divide under (possibly emulated) f64 — on a backend whose emulation is not
# IEEE-correct the host check would promise an exactness the device cannot
# deliver. The canary replays representative carriers for EVERY scale through
# the same jitted divide (runtime scale argument, exactly like
# WidenSpec.widen) at first upload; any mismatch disables scaled-decimal
# shrinking process-wide and those columns fall back to wide lanes
# (f32 round-trip or raw f64). Round-5 advisor item.
_decimal_canary_ok: Optional[bool] = None
# two first-uploads on different threads (serving tier) must not both run the
# canary and race the verdict write; compute-once under a lock. Tests may
# still poke `codec._decimal_canary_ok` directly (the read below is lock-free
# once the verdict exists).
_canary_lock = threading.Lock()


def reset_decimal_canary() -> None:
    """Test-visible reset hook: forget the canary verdict so test order (or a
    backend flip under the same process) cannot leak a stale verdict."""
    global _decimal_canary_ok
    with _canary_lock:
        _decimal_canary_ok = None


def _scaled_decimal_ok() -> bool:
    if _decimal_canary_ok is not None:
        return _decimal_canary_ok
    with _canary_lock:
        return _scaled_decimal_ok_locked()


def _scaled_decimal_ok_locked() -> bool:
    global _decimal_canary_ok
    if _decimal_canary_ok is None:
        import jax
        import jax.numpy as jnp
        ok = True
        # carriers spanning the int32 range incl. values whose quotient is
        # inexact in binary (odd cents / odd hundredths of cents)
        c = np.concatenate([
            np.arange(-999, 1000, 7, dtype=np.int64),
            np.asarray([_I32[0], _I32[1], 1, -1, 3, 99, 12345679,
                        987654321, -123456789], dtype=np.int64)])
        try:
            div = jax.jit(lambda a, s: a.astype(jnp.float64) / s)
            for scale in _FLOAT_SCALES:
                host = c.astype(np.float64) / np.float64(scale)
                dev = np.asarray(div(jnp.asarray(c.astype(np.int32)),
                                     jnp.asarray(np.float64(scale))))
                if not np.array_equal(dev, host):
                    ok = False
                    break
        except Exception:
            ok = False
        _decimal_canary_ok = ok
        from igloo_tpu.utils import tracing
        tracing.counter("codec.decimal_canary_ok" if ok
                        else "codec.decimal_canary_fail")
        if not ok:
            tracing.log.warning(
                "codec: on-device scaled-decimal canary FAILED; decimal "
                "columns will ship as wide lanes (f32/f64) instead")
    return _decimal_canary_ok


def _shrink_float(v: np.ndarray, lane: np.dtype):
    """Scaled-decimal or f32 round-trip shrink for a float array."""
    if v.size == 0:
        return v.astype(np.int8), WidenSpec(lane.name)
    finite = np.isfinite(v)
    if finite.all():
        for scale in _FLOAT_SCALES:
            # scale 1.0 widens by pure int->float CAST (no division), so it
            # needs no canary; the divided scales are gated on the device
            # replaying the host-verified divide bit-for-bit
            if scale != 1.0 and not _scaled_decimal_ok():
                continue
            c = np.rint(v * scale)
            if not ((c >= _I32[0]).all() and (c <= _I32[1]).all()):
                continue
            ci = c.astype(np.int64)
            # exact host verification: the device replays this same divide
            if not np.array_equal(ci.astype(lane) / lane.type(scale), v):
                continue
            shrunk = _shrink_int(ci, np.dtype(np.int64))
            if shrunk is not None and shrunk[0].dtype.itemsize < lane.itemsize:
                nv, _ = shrunk
                if shrunk[1].offset == 0:
                    return nv, WidenSpec(lane.name, scale=scale)
            if lane.itemsize > 4:
                return ci.astype(np.int32), WidenSpec(lane.name, scale=scale)
            break
    if lane == np.float64:
        f32 = v.astype(np.float32)
        if np.array_equal(f32.astype(np.float64), v, equal_nan=True):
            return f32, WidenSpec(lane.name)
    return None


def shrink(np_vals: np.ndarray, lane: np.dtype):
    """-> (carrier ndarray, WidenSpec) | None when no narrowing applies.

    `np_vals` must already be in the engine lane dtype (nulls pre-filled with
    0/False so sentinel values cannot break range analysis)."""
    if lane.kind in ("i", "u") and np_vals.dtype == lane:
        return _shrink_int(np_vals, lane)
    if lane.kind == "f" and np_vals.dtype == lane:
        return _shrink_float(np_vals, lane)
    return None


def _pad_to(a: np.ndarray, cap: int) -> np.ndarray:
    if len(a) == cap:
        return a
    out = np.zeros((cap,), dtype=a.dtype)
    out[: len(a)] = a
    return out


# --- run-length transfer carrier --------------------------------------------
# Sorted/clustered columns (l_shipdate-shaped after a clustered read) collapse
# to a handful of runs; shipping (run values, run starts) instead of the full
# carrier lane cuts H2D a further order of magnitude. RLE exists only on the
# wire: the device expands it back to the SCALAR narrow carrier in one jit, so
# downstream filters/segment ops run on the per-row carrier lane and nothing
# else in the engine needs run awareness.

#: engage RLE only when the column is long enough to matter and the run count
#: is a small fraction of the rows (the two shipped arrays must clearly win)
RLE_MIN_ROWS = 1024
RLE_MAX_RUN_FRACTION = 8  # runs <= n // 8


def rle_encode(arr: np.ndarray):
    """-> (run_values, run_starts int32) | None when RLE does not pay.
    `run_starts[0]` is always 0; run k covers rows
    [run_starts[k], run_starts[k+1])."""
    n = len(arr)
    if n < RLE_MIN_ROWS or arr.dtype.kind not in ("i", "u"):
        return None
    change = np.nonzero(arr[1:] != arr[:-1])[0]
    if len(change) + 1 > n // RLE_MAX_RUN_FRACTION:
        return None
    starts = np.concatenate([[0], change + 1]).astype(np.int32)
    return arr[starts], starts


def rle_decode(run_values: np.ndarray, run_starts: np.ndarray,
               n: int) -> np.ndarray:
    """Host-side inverse of `rle_encode` (tests / host-tier consumers)."""
    idx = np.searchsorted(run_starts, np.arange(n), side="right") - 1
    return run_values[idx]


@functools.lru_cache(maxsize=256)
def _rle_expand_jit(runs_cap: int, cap: int, dtype_name: str):
    def fn(rv, starts):
        idx = jnp.searchsorted(starts, jnp.arange(cap, dtype=jnp.int32),
                               side="right") - 1
        return jnp.take(rv, jnp.clip(idx, 0, runs_cap - 1))
    return jax.jit(fn)


def upload_columns(plans: list, device=None) -> list:
    """Upload a batch of columns, keeping carriers RESIDENT on device.

    `plans` is a list of (np_array, lane_dtype | None, capacity); lane None
    means the array ships as-is after padding (bool masks). Narrowing is
    decided over the UNPADDED values (so pad zeros cannot drag the value
    range) and the carrier is zero-padded — a dead lane therefore widens to
    the spec's offset, which is 0 on every path except offset-shrink.

    Returns one (device_array, spec, carrier_arg) triple per plan, order
    preserved. `spec` is the CANONICAL WidenSpec (offset presence only — the
    real offset rides in `carrier_arg`, a 0-d device array, so distinct column
    minima share compiled programs); spec None means the lane shipped wide.
    The narrow array is what stays in HBM: operators widen in-jit through
    `batch.wide_values` (XLA fuses the cast/divide into the consumer), so HBM
    residency and every downstream byte cost scale with carrier width.

    With IGLOO_TPU_ENCODED=0 every column ships and resides WIDE (the
    bit-identical kill switch; also the `codec.*` counter A/B baseline).
    Sorted/clustered integer carriers additionally ship run-length encoded
    (IGLOO_TPU_RLE) and expand to the scalar carrier in one device jit."""
    raw_put = (jnp.asarray if device is None
               else functools.partial(jax.device_put, device=device))
    h2d = 0

    def put(a):
        nonlocal h2d
        h2d += getattr(a, "nbytes", 0)
        return raw_put(a)

    from igloo_tpu.utils import tracing
    enc = encoded_enabled()
    rle = rle_enabled()
    out: list = [None] * len(plans)
    carrier_bytes = 0
    decoded_bytes = 0
    for i, (arr, lane, cap) in enumerate(plans):
        shrunk = shrink(arr, np.dtype(lane)) \
            if (enc and lane is not None) else None
        if shrunk is None:
            out[i] = (put(_pad_to(arr, cap)), None, None)
            if lane is not None:
                decoded_bytes += cap * np.dtype(lane).itemsize
                carrier_bytes += cap * arr.dtype.itemsize
            continue
        carrier, spec = shrunk
        decoded_bytes += cap * np.dtype(lane).itemsize
        runs = rle_encode(carrier) if rle else None
        if runs is not None:
            rv, starts = runs
            runs_cap = round_capacity_for_runs(len(rv))
            dev_rv = put(_pad_to(rv, runs_cap))
            # pad starts with `cap` (past every real row) so the expand's
            # searchsorted maps dead run slots past the data
            pstarts = np.full((runs_cap,), cap, dtype=np.int32)
            pstarts[: len(starts)] = starts
            dev_starts = put(pstarts)
            vals = _rle_expand_jit(runs_cap, cap, rv.dtype.name)(
                dev_rv, dev_starts)
            tracing.counter("codec.rle_columns")
            carrier_bytes += int(dev_rv.nbytes + dev_starts.nbytes)
        else:
            vals = put(_pad_to(carrier, cap))
            carrier_bytes += cap * carrier.dtype.itemsize
        # canonical spec + runtime 0-d payload: the offset is data-dependent
        # (column min), the scale divisor must stay a runtime operand so XLA
        # cannot rewrite the divide into an inexact reciprocal multiply
        if spec.offset:
            carg = put(np.int64(spec.offset))
            cspec = WidenSpec(spec.lane, offset=1)
        elif spec.scale != 1.0:
            carg = put(np.float64(spec.scale))
            cspec = WidenSpec(spec.lane, scale=spec.scale)
        else:
            carg = None
            cspec = WidenSpec(spec.lane)
        out[i] = (vals, cspec, carg)
    if carrier_bytes:
        tracing.counter("codec.carrier_bytes", carrier_bytes)
    if decoded_bytes:
        tracing.counter("codec.decoded_bytes", decoded_bytes)
    from igloo_tpu.utils.stats import record_upload
    record_upload(h2d)  # actual shipped bytes: narrowed carriers, padded
    return out


def round_capacity_for_runs(nruns: int) -> int:
    """Shape-bucket the RLE run arrays like every other lane so the expand
    jit cache stays small."""
    from igloo_tpu.exec.capacity import canonical_capacity
    return canonical_capacity(max(nruns, 1))


def host_widen(spec: WidenSpec, vals: np.ndarray, carg=None) -> np.ndarray:
    """Decode a fetched carrier lane back to the engine lane ON HOST, at the
    output boundary (batch.arrow_from_host). Bit-identical to the device
    widen: the offset path is exact integer addition, the scale path replays
    the very IEEE-f64 divide `_shrink_float` verified elementwise, and the
    cast paths (f32->f64, int8->int64) are exact by construction."""
    lane = np.dtype(spec.lane)
    if spec.scale != 1.0:
        return vals.astype(lane) / lane.type(spec.scale)
    if spec.offset:
        off = int(carg) if carg is not None else spec.offset
        return vals.astype(lane) + lane.type(off)
    return vals.astype(lane, copy=False)


# --- measured carrier ratio: plan pricing in carrier bytes -------------------
# The chunked/GRACE budget math and serving's predict_hbm_bytes estimate plans
# in WIDE lane bytes (chunked.estimated_lane_bytes). Once a provider's columns
# have actually shipped, the observed narrow/wide ratio is remembered PER
# PROVIDER INSTANCE and those estimators scale by it — so more queries admit
# concurrently and effective partitions grow per HBM budget. Keyed weakly so a
# dropped provider cannot pin its entry; unmeasured providers price at 1.0
# (estimates never shrink on faith).

import weakref

_RATIO_LOCK = threading.Lock()
_CARRIER_RATIOS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def record_carrier_ratio(provider, narrow_bytes: int,
                         wide_bytes: int) -> None:
    if provider is None or wide_bytes <= 0 or not encoded_enabled():
        return
    ratio = min(max(narrow_bytes / wide_bytes, 0.0), 1.0)
    try:
        with _RATIO_LOCK:
            _CARRIER_RATIOS[provider] = ratio
    except TypeError:
        pass  # non-weakref-able provider: price wide, never crash


def reset_carrier_ratios() -> None:
    """Forget every measured ratio — restores the price-wide-until-measured
    cold state. For tests and A/B bench runs that need plan pricing (and so
    chunked/GRACE/admission routing) independent of which queries ran
    earlier in the process."""
    with _RATIO_LOCK:
        _CARRIER_RATIOS.clear()


def carrier_ratio(provider) -> float:
    """Measured carrier/wide byte ratio for this provider instance, or 1.0
    when unmeasured (or the kill switch is off)."""
    if provider is None or not encoded_enabled():
        return 1.0
    try:
        with _RATIO_LOCK:
            return _CARRIER_RATIOS.get(provider, 1.0)
    except TypeError:
        return 1.0


@functools.lru_cache(maxsize=64)
def _live_jit(cap: int):
    return jax.jit(lambda n: jnp.arange(cap, dtype=jnp.int32) < n)


def live_lane(cap: int, n: int, device=None):
    """Selection mask with the first `n` lanes set, built ON DEVICE from a
    4-byte scalar instead of shipping `cap` bool bytes over the tunnel."""
    nn = np.int32(n)
    nd = jnp.asarray(nn) if device is None else jax.device_put(nn, device)
    return _live_jit(int(cap))(nd)
