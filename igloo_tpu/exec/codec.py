"""Lossless narrow-transfer codec for the host->HBM boundary.

The engine computes on int64/float64 lanes (SQL semantics), but shipping those
lanes verbatim wastes the scarcest resource on a tunneled TPU: host<->device
bandwidth (measured ~10-20 MB/s through the axon tunnel, flat per byte — see
BASELINE.md). A 6M-row float64 column is 48 MB on the wire even when every
value is a whole number under 50.

This codec picks, per column and on the host, the smallest *provably lossless*
carrier representation, uploads that, and widens back to the engine lane dtype
on device inside ONE fused jit per batch (so the widening costs one dispatch,
not one per column). Carriers, tried narrowest-first:

- integer family (int64/int32/date32/timestamp lanes): offset shrink —
  ``carrier = v - off`` cast to int8/int16/int32 when the value RANGE fits;
  widen = ``carrier.astype(lane) + off``. Exact by construction.
- float lanes: scaled-decimal shrink — ``c = rint(v * scale)`` for scale in
  {1, 100, 10000} when c fits int32 AND ``c / scale == v`` elementwise on the
  host (float64 division, verified value by value); widen =
  ``c.astype(f64) / scale``. TPC-H prices/discounts/taxes are decimals with
  <= 4 fractional digits, so they ride int8/int16/int32 carriers. IEEE-754
  division is deterministic, so the host check guarantees the device result
  bit-for-bit (the TPU's emulated f64 divide is IEEE-correct; verified by
  tests/test_codec.py on CPU and by the bench harness on device).
- float64 -> float32 round-trip: when ``v == f32(v)`` exactly (NaN-aware).
- everything else ships as the lane dtype unchanged.

The reference engine has no analog (it streams Arrow RecordBatches in-process,
reference crates/engine/src/operators/parquet_scan.rs:40-85); this boundary
exists only because the TPU sits across an interconnect.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_I8 = (-(1 << 7), (1 << 7) - 1)
_I16 = (-(1 << 15), (1 << 15) - 1)
_I32 = (-(1 << 31), (1 << 31) - 1)
_INT_STEPS = ((np.int8, _I8), (np.int16, _I16), (np.int32, _I32))


@dataclass(frozen=True)
class WidenSpec:
    """How to reconstruct the engine lane from the carrier, on device.

    lane:   target numpy dtype name ('int64', 'float64', ...)
    offset: integer added after the cast (int paths; 0 for float paths)
    scale:  divisor applied after the cast (float paths; 1 = none)
    """
    lane: str
    offset: int = 0
    scale: float = 1.0

    def widen(self, a: jax.Array, scale_arg=None, offset_arg=None) -> jax.Array:
        """`scale_arg`/`offset_arg`, when given, must be RUNTIME 0-d arrays
        holding self.scale/self.offset. Scale: baking the divisor in as a
        constant lets XLA rewrite the divide into a multiply by the (inexact)
        reciprocal, which breaks the host-verified exactness for ~13% of
        scaled-decimal values. Offset: it is data-dependent (the column min),
        so baking it in would compile a fresh widen program per distinct min
        (one per chunk in the chunked executor)."""
        lane = jnp.dtype(self.lane)
        if self.scale != 1.0:
            s = (scale_arg.astype(lane) if scale_arg is not None
                 else lane.type(self.scale))
            return a.astype(lane) / s
        if self.offset:
            off = (offset_arg.astype(lane) if offset_arg is not None
                   else lane.type(self.offset))
            return a.astype(lane) + off
        if a.dtype != lane:
            return a.astype(lane)
        return a

    def key(self) -> tuple:
        """Static jit-cache key: everything EXCEPT the data-dependent payload
        values (offset rides in at runtime; only its presence is static)."""
        return (self.lane, self.scale != 1.0, self.scale, bool(self.offset))


def _shrink_int(v: np.ndarray, lane: np.dtype):
    """Offset-shrink an integer array; None when it cannot shrink."""
    if v.size == 0:
        return v.astype(np.int8), WidenSpec(lane.name)
    lo, hi = int(v.min()), int(v.max())
    for nd, (nlo, nhi) in _INT_STEPS:
        nd_ = np.dtype(nd)
        if nd_.itemsize >= lane.itemsize:
            return None
        span = hi - lo
        if span <= nhi - nlo:
            # center the carrier range when an offset is needed at all
            off = 0 if (nlo <= lo and hi <= nhi) else lo - nlo
            return (v - off).astype(nd), WidenSpec(lane.name, offset=off)
    return None


_FLOAT_SCALES = (1.0, 100.0, 10000.0)

# one-time on-device canary for the scaled-decimal path: None = not yet run.
# The host verifies ``c / scale == v`` in IEEE f64, but the device replays the
# divide under (possibly emulated) f64 — on a backend whose emulation is not
# IEEE-correct the host check would promise an exactness the device cannot
# deliver. The canary replays representative carriers for EVERY scale through
# the same jitted divide (runtime scale argument, exactly like
# WidenSpec.widen) at first upload; any mismatch disables scaled-decimal
# shrinking process-wide and those columns fall back to wide lanes
# (f32 round-trip or raw f64). Round-5 advisor item.
_decimal_canary_ok: Optional[bool] = None


def _scaled_decimal_ok() -> bool:
    global _decimal_canary_ok
    if _decimal_canary_ok is None:
        import jax
        import jax.numpy as jnp
        ok = True
        # carriers spanning the int32 range incl. values whose quotient is
        # inexact in binary (odd cents / odd hundredths of cents)
        c = np.concatenate([
            np.arange(-999, 1000, 7, dtype=np.int64),
            np.asarray([_I32[0], _I32[1], 1, -1, 3, 99, 12345679,
                        987654321, -123456789], dtype=np.int64)])
        try:
            div = jax.jit(lambda a, s: a.astype(jnp.float64) / s)
            for scale in _FLOAT_SCALES:
                host = c.astype(np.float64) / np.float64(scale)
                dev = np.asarray(div(jnp.asarray(c.astype(np.int32)),
                                     jnp.asarray(np.float64(scale))))
                if not np.array_equal(dev, host):
                    ok = False
                    break
        except Exception:
            ok = False
        _decimal_canary_ok = ok
        from igloo_tpu.utils import tracing
        tracing.counter("codec.decimal_canary_ok" if ok
                        else "codec.decimal_canary_fail")
        if not ok:
            tracing.log.warning(
                "codec: on-device scaled-decimal canary FAILED; decimal "
                "columns will ship as wide lanes (f32/f64) instead")
    return _decimal_canary_ok


def _shrink_float(v: np.ndarray, lane: np.dtype):
    """Scaled-decimal or f32 round-trip shrink for a float array."""
    if v.size == 0:
        return v.astype(np.int8), WidenSpec(lane.name)
    finite = np.isfinite(v)
    if finite.all():
        for scale in _FLOAT_SCALES:
            # scale 1.0 widens by pure int->float CAST (no division), so it
            # needs no canary; the divided scales are gated on the device
            # replaying the host-verified divide bit-for-bit
            if scale != 1.0 and not _scaled_decimal_ok():
                continue
            c = np.rint(v * scale)
            if not ((c >= _I32[0]).all() and (c <= _I32[1]).all()):
                continue
            ci = c.astype(np.int64)
            # exact host verification: the device replays this same divide
            if not np.array_equal(ci.astype(lane) / lane.type(scale), v):
                continue
            shrunk = _shrink_int(ci, np.dtype(np.int64))
            if shrunk is not None and shrunk[0].dtype.itemsize < lane.itemsize:
                nv, _ = shrunk
                if shrunk[1].offset == 0:
                    return nv, WidenSpec(lane.name, scale=scale)
            if lane.itemsize > 4:
                return ci.astype(np.int32), WidenSpec(lane.name, scale=scale)
            break
    if lane == np.float64:
        f32 = v.astype(np.float32)
        if np.array_equal(f32.astype(np.float64), v, equal_nan=True):
            return f32, WidenSpec(lane.name)
    return None


def shrink(np_vals: np.ndarray, lane: np.dtype):
    """-> (carrier ndarray, WidenSpec) | None when no narrowing applies.

    `np_vals` must already be in the engine lane dtype (nulls pre-filled with
    0/False so sentinel values cannot break range analysis)."""
    if lane.kind in ("i", "u") and np_vals.dtype == lane:
        return _shrink_int(np_vals, lane)
    if lane.kind == "f" and np_vals.dtype == lane:
        return _shrink_float(np_vals, lane)
    return None


@functools.lru_cache(maxsize=512)
def _widen_jit(specs: tuple, caps: tuple):
    """One jit that widens a whole batch of carriers in a single dispatch.
    Scales and offsets ride in as runtime vectors (see WidenSpec.widen);
    `specs` here are the data-independent WidenSpec.key() tuples plus carrier
    dtypes, so distinct column minima share one compiled program."""
    def fn(arrs, scales, offsets):
        out = []
        for i, ((lane, scaled, scale, has_off), a) in enumerate(
                zip(specs, arrs)):
            spec = WidenSpec(lane, offset=1 if has_off else 0,
                             scale=scale if scaled else 1.0)
            out.append(spec.widen(a, scales[i] if scaled else None,
                                  offsets[i] if has_off else None))
        return out
    return jax.jit(fn)


def _pad_to(a: np.ndarray, cap: int) -> np.ndarray:
    if len(a) == cap:
        return a
    out = np.zeros((cap,), dtype=a.dtype)
    out[: len(a)] = a
    return out


def upload_columns(plans: list, device=None) -> list:
    """Upload a batch of columns with narrowing, ONE widen dispatch total.

    `plans` is a list of (np_array, lane_dtype | None, capacity); lane None
    means the array ships as-is after padding (bool masks). Narrowing is
    decided over the UNPADDED values (so pad zeros cannot drag the value range)
    and the carrier is zero-padded — a dead lane therefore widens to the
    spec's offset, which is 0 on every path except offset-shrink. Returns the
    device arrays in the engine lane dtypes, order preserved."""
    raw_put = (jnp.asarray if device is None
               else functools.partial(jax.device_put, device=device))
    h2d = 0

    def put(a):
        nonlocal h2d
        h2d += getattr(a, "nbytes", 0)
        return raw_put(a)

    out: list = [None] * len(plans)
    widen_idx: list[int] = []
    widen_specs: list[WidenSpec] = []
    widen_arrs: list = []
    for i, (arr, lane, cap) in enumerate(plans):
        shrunk = shrink(arr, np.dtype(lane)) if lane is not None else None
        if shrunk is None:
            out[i] = put(_pad_to(arr, cap))
            continue
        carrier, spec = shrunk
        widen_idx.append(i)
        widen_specs.append(spec)
        widen_arrs.append(put(_pad_to(carrier, cap)))
    if widen_idx:
        caps = tuple((a.shape, a.dtype.name) for a in widen_arrs)
        scales = put(np.asarray([s.scale for s in widen_specs],
                                dtype=np.float64))
        offsets = put(np.asarray([s.offset for s in widen_specs],
                                 dtype=np.int64))
        wide = _widen_jit(tuple(s.key() for s in widen_specs), caps)(
            widen_arrs, scales, offsets)
        for i, w in zip(widen_idx, wide):
            out[i] = w
    from igloo_tpu.utils.stats import record_upload
    record_upload(h2d)  # actual shipped bytes: narrowed carriers, padded
    return out


@functools.lru_cache(maxsize=64)
def _live_jit(cap: int):
    return jax.jit(lambda n: jnp.arange(cap, dtype=jnp.int32) < n)


def live_lane(cap: int, n: int, device=None):
    """Selection mask with the first `n` lanes set, built ON DEVICE from a
    4-byte scalar instead of shipping `cap` bool bytes over the tunnel."""
    nn = np.int32(n)
    nd = jnp.asarray(nn) if device is None else jax.device_put(nn, device)
    return _live_jit(int(cap))(nd)
