"""Shared device kernel primitives.

These are the building blocks the reference implements as per-row Rust loops
(hash_join.rs:116-211 row-at-a-time build/probe, filter.rs:47-57 per-batch eval) —
re-designed as static-shape, whole-column XLA programs:

- key normalization: any column -> int64 "key lane(s)" whose ordering/equality
  matches SQL semantics (floats via order-preserving bit tricks, strings via
  sorted-dictionary ids or dictionary hash lanes for cross-table equality)
- lexicographic argsort via iterated stable sorts (the TPU-friendly way to sort
  multi-key rows: no comparators, just k stable sorts of an index permutation)
- group boundary detection + segment ids for segment-reduce aggregation
- selection-mask compaction (stable partition live-to-front) — the static-shape
  replacement for the reference's eager `filter_record_batch`
- 64-bit avalanche hashing for multi-lane join keys (verified exactly afterwards,
  so collisions cost slots, never correctness)
"""
from __future__ import annotations

from dataclasses import replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from igloo_tpu import types as T
from igloo_tpu.exec.batch import DeviceBatch, DeviceColumn, DictInfo

# splitmix64 constants (public-domain finalizer)
_C1 = np.int64(np.uint64(0xBF58476D1CE4E5B9).astype(np.int64))
_C2 = np.int64(np.uint64(0x94D049BB133111EB).astype(np.int64))
_GOLDEN = np.int64(np.uint64(0x9E3779B97F4A7C15).astype(np.int64))


def mix64(x: jax.Array) -> jax.Array:
    """splitmix64 avalanche over an int64 lane."""
    x = x.astype(jnp.int64)
    ux = x.astype(jnp.uint64)
    ux = ux ^ (ux >> np.uint64(30))
    ux = ux * np.uint64(0xBF58476D1CE4E5B9)
    ux = ux ^ (ux >> np.uint64(27))
    ux = ux * np.uint64(0x94D049BB133111EB)
    ux = ux ^ (ux >> np.uint64(31))
    return ux.astype(jnp.int64)


def hash_lanes(lanes: list[jax.Array], nulls: list[Optional[jax.Array]]) -> jax.Array:
    """Combine key lanes into one well-mixed int64 per row. NULL contributes a
    distinct tag so (1, NULL) != (1, 2) pre-verification."""
    h = jnp.full(lanes[0].shape, _GOLDEN, dtype=jnp.int64)
    for lane, nl in zip(lanes, nulls):
        v = lane.astype(jnp.int64)
        if nl is not None:
            v = jnp.where(nl, np.int64(-0x61C8864680B583EB), v)
        h = mix64(h ^ mix64(v))
    return h


def normalize_float(x: jax.Array):
    """Canonicalize a float lane for grouping/hashing WITHOUT 64-bit bitcasts
    (the TPU X64 rewriter does not implement f64<->s64 bitcast-convert): returns
    (vnorm, nan_flag) where -0.0 -> +0.0 and every NaN collapses to 0.0 with the
    flag set. Equality on (vnorm, nan_flag) == SQL grouping equality; ordering on
    them (NaN flag as a more significant lane) == SQL "NaN sorts greatest"."""
    xf = x
    xf = jnp.where(xf == 0.0, jnp.zeros((), xf.dtype), xf)
    nan = jnp.isnan(xf)
    return jnp.where(nan, jnp.zeros((), xf.dtype), xf), nan


def float_hash_int_lanes(x: jax.Array) -> list[jax.Array]:
    """Deterministic int64 lanes for hashing a float lane, bitcast-free: integer
    part + scaled fraction + nan flag. Equal floats always map to equal lanes
    (required); nearby floats may collide (harmless — joins verify exactly)."""
    vnorm, nan = normalize_float(x)
    v = vnorm.astype(jnp.float64)
    # clamp so .astype(int64) is defined, keep determinism
    bounded = jnp.clip(v, -9.0e15, 9.0e15)
    ipart = bounded.astype(jnp.int64)
    frac = (bounded - ipart.astype(jnp.float64)) * np.float64(2.0 ** 52)
    return [ipart, frac.astype(jnp.int64), nan.astype(jnp.int64)]


def sort_lanes_for(v: jax.Array, null: Optional[jax.Array], is_float: bool,
                   ascending: bool, nulls_first: bool) -> list[tuple]:
    """Decompose one sort key into [(lane, ascending_flag), ...] most-significant
    first: null ordering lane, NaN lane (floats; NaN sorts greatest), value lane.
    Works for any lane dtype jnp.argsort accepts — no int64 bit tricks."""
    lanes: list[tuple] = []
    if null is None:
        nkey = jnp.zeros(v.shape, dtype=jnp.int32)
    else:
        nkey = jnp.where(null, np.int32(-1 if nulls_first else 1), np.int32(0))
    lanes.append((nkey, True))
    if is_float:
        vnorm, nan = normalize_float(v)
        lanes.append((nan.astype(jnp.int32), ascending))  # NaN greatest
        val = vnorm
    else:
        val = v
    if null is not None:
        val = jnp.where(null, jnp.zeros((), val.dtype), val)
    lanes.append((val, ascending))
    return lanes


def group_lanes_for(v: jax.Array, is_float: bool) -> list[jax.Array]:
    """Equality lanes for grouping: floats become (nan_flag, vnorm)."""
    if is_float:
        vnorm, nan = normalize_float(v)
        return [nan.astype(jnp.int32), vnorm]
    return [v]


def _argsort_dir(lane: jax.Array, ascending: bool) -> jax.Array:
    if ascending:
        return jnp.argsort(lane, stable=True)
    if lane.dtype == jnp.bool_:
        lane = lane.astype(jnp.int32)
    return jnp.argsort(-lane, stable=True)


def lex_argsort(lanes: list, live: jax.Array) -> jax.Array:
    """Stable lexicographic argsort. `lanes` = [(lane, ascending), ...]
    most-significant first. Dead rows always sort last. Returns permutation."""
    n = live.shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    # iterated stable sorts from least-significant lane to most-significant
    for lane, asc in reversed(lanes):
        perm = perm[_argsort_dir(jnp.take(lane, perm), asc)]
    # dead rows last (most significant)
    perm = perm[jnp.argsort(jnp.take(~live, perm), stable=True)]
    return perm


# ---------------------------------------------------------------------------
# Packed sort keys: fuse a multi-lane lexicographic key into ONE integer lane.
#
# The multi-lane chain (lex_argsort) pays one full stable sort per lane; a
# TPC-H q18-shaped group-by carries 5 keys = 10+ lanes = 10+ 8M-lane sorts.
# When every key is integer-family (ints, dates, timestamps, bools, dictionary
# ids) with host-known value bounds (scan stats, DeviceColumn.bounds), the keys
# bit-pack into one minimal-width integer digit string whose ordering equals
# the lexicographic ordering — ONE argsort replaces the whole chain, and when
# the digits fit 30 bits the lane is int32, halving sort bytes.
#
# Encoding per key (radix `card`, runtime offset `lo`):
#   value digit vd = v - lo            (descending keys: vd = card-2 - vd)
#   nulls-first:    digit = 0 for NULL else vd + 1
#   nulls-last:     digit = card-1 for NULL else vd
# Digits combine most-significant-first: acc = acc * card + digit. Radices are
# rounded to powers of two and offsets ride the ConstPool as RUNTIME data, so
# two executions whose bounds differ only in position (data refreshes, GRACE
# partitions) share one compiled program — only the radix bucket is static.
#
# Fallback ladder: packed int32 (<= 30 digit bits) -> packed int64 (<= 62) ->
# the multi-lane lex_argsort chain. One bit is always reserved for the
# dead-row sentinel (packed_sort_key), hence 62/30, not 63/31.
# ---------------------------------------------------------------------------

PACK_BITS_I64 = 62
PACK_BITS_I32 = 30


def _pack_card(lo: int, hi: int) -> int:
    """Per-key digit radix: power-of-two bucket of (span + NULL digit + 1
    headroom slot, so nulls-first and nulls-last encodings share one radix)."""
    span = int(hi) - int(lo) + 1
    card = 2
    while card < span + 2:
        card <<= 1
    return card


def _key_pack_range(k):
    """Host-known (lo, hi) value range of one key (a Compiled-shaped object:
    .dtype / .out_dict / .out_bounds), or None when the key cannot pack.
    Strings pack by dictionary id — callers that need ORDER semantics must
    ensure ids are ranks (sorted dictionary) before planning."""
    dt = k.dtype
    if dt.id == T.TypeId.BOOL:
        return (0, 1)
    if dt.is_string:
        d = k.out_dict
        if d is None:
            return None
        return (0, max(len(d) - 1, 0))
    if (dt.is_integer or dt.is_temporal) and k.out_bounds is not None:
        return (int(k.out_bounds[0]), int(k.out_bounds[1]))
    return None


def _build_pack_spec(ranges: list, ascending: list, nulls_first: list, pool):
    """(lane_tag, offsets_pool_idx, ((card, asc, nulls_first), ...)) or None
    when the digits exceed the int64 budget. Hashable: safe in jit cache keys."""
    digits = []
    offsets = []
    total = 1
    for (lo, hi), asc, nf in zip(ranges, ascending, nulls_first):
        card = _pack_card(lo, hi)
        total *= card
        if total > (1 << PACK_BITS_I64):
            return None
        offsets.append(int(lo))
        digits.append((card, bool(asc), bool(nf)))
    lane = "i32" if total <= (1 << PACK_BITS_I32) else "i64"
    oidx = pool.add(np.asarray(offsets, dtype=np.int64))
    return (lane, oidx, tuple(digits))


def plan_group_packing(keys: list, pool):
    """Pack plan for GROUP BY keys: grouping equality is symmetric, so ANY
    subset of the keys may fuse into the packed lane (unlike ORDER BY, which
    is limited to a prefix) — a q18-shaped 5-key group-by with one float key
    packs the other four; the aggregate kernel then folds the float's
    null/NaN flags into the packed lane's spare bits and sorts TWO lanes
    instead of 10+. Returns (spec, packed_key_indices) or None when packing
    would not drop at least one sort pass (fewer than 2 packable keys, unless
    that single packable key is the whole key set)."""
    if not keys:
        return None
    ranges = []
    idxs = []
    total = 1
    for i, k in enumerate(keys):
        r = _key_pack_range(k)
        if r is None:
            continue
        card = _pack_card(*r)
        if total * card > (1 << PACK_BITS_I64):
            continue
        total *= card
        ranges.append(r)
        idxs.append(i)
    if not idxs or (len(idxs) < 2 and len(idxs) != len(keys)):
        return None
    n = len(idxs)
    spec = _build_pack_spec(ranges, [True] * n, [True] * n, pool)
    if spec is None:
        return None
    return spec, tuple(idxs)


def plan_prefix_packing(keys: list, ascending, nulls_first, pool):
    """Longest packable key PREFIX (most-significant keys first) for ORDER BY:
    returns (spec, n_keys_packed) or None. A partial pack still pays: the
    prefix collapses to one lex_argsort lane ahead of the unpackable tail."""
    ranges = []
    total = 1
    for k in keys:
        if k.dtype.is_string and \
                (k.out_dict is None or not k.out_dict.is_sorted):
            break
        r = _key_pack_range(k)
        if r is None:
            break
        if total * _pack_card(*r) > (1 << PACK_BITS_I64):
            break
        total *= _pack_card(*r)
        ranges.append(r)
    npk = len(ranges)
    if npk == 0:
        return None
    spec = _build_pack_spec(ranges, list(ascending)[:npk],
                            list(nulls_first)[:npk], pool)
    if spec is None:
        return None
    return spec, npk


def plan_pair_packing(left_keys: list, right_keys: list, pool):
    """Shared pack spec for a join's residual-equality lanes: every key pair
    must be integer-family on BOTH sides with host-known bounds; the digit
    range is the union of the two sides' ranges (so equal values share a digit
    across tables). Strings never qualify — their ids are per-dictionary."""
    if not left_keys or len(left_keys) != len(right_keys):
        return None
    ranges = []
    for lk, rk in zip(left_keys, right_keys):
        if lk.dtype.is_string or rk.dtype.is_string:
            return None
        rl, rr = _key_pack_range(lk), _key_pack_range(rk)
        if rl is None or rr is None:
            return None
        ranges.append((min(rl[0], rr[0]), max(rl[1], rr[1])))
    n = len(ranges)
    return _build_pack_spec(ranges, [True] * n, [True] * n, pool)


def pack_key_lane(spec: tuple, vals: list, nulls: list,
                  consts: tuple) -> jax.Array:
    """Jit-traceable: normalized mixed-radix key digits -> one int lane whose
    ascending order IS the keys' lexicographic order (per-key direction and
    null placement baked into the digits). NULL lanes are replaced BEFORE the
    radix combine, so garbage values under a null mask cannot poison other
    keys' digits; dead-lane garbage wraps harmlessly and is overwritten by the
    packed_sort_key sentinel before any consumer reads it."""
    lane_tag, oidx, digits = spec
    offsets = consts[oidx]
    acc = None
    for i, ((card, asc, nf), v, nl) in enumerate(zip(digits, vals, nulls)):
        vd = v.astype(jnp.int64) - offsets[i]
        if not asc:
            vd = np.int64(card - 2) - vd
        if nf:
            d = vd + np.int64(1)
            if nl is not None:
                d = jnp.where(nl, np.int64(0), d)
        else:
            d = vd
            if nl is not None:
                d = jnp.where(nl, np.int64(card - 1), d)
        acc = d if acc is None else acc * np.int64(card) + d
    if lane_tag == "i32":
        return acc.astype(jnp.int32)
    return acc


def unpack_key_digits(spec: tuple, packed: jax.Array, consts: tuple):
    """Inverse of `pack_key_lane` for the all-ascending nulls-first encoding
    `plan_group_packing` emits: packed int lane -> ([per-key value lanes],
    [per-key null flags]). Digit 0 is NULL; otherwise value = digit - 1 +
    offset. Used by the Pallas hash-aggregate path to decode group key
    columns straight from the stored table keys (the packed lane is a
    bijection of its digit string, so no first-occurrence scatter)."""
    lane_tag, oidx, digits = spec
    offsets = consts[oidx]
    acc = packed.astype(jnp.int64)
    strides = []
    s = 1
    for card, _asc, _nf in reversed(digits):
        strides.append(s)
        s *= card
    strides.reverse()
    vals, nulls = [], []
    for i, ((card, _asc, _nf), st) in enumerate(zip(digits, strides)):
        d = (acc // np.int64(st)) % np.int64(card)
        nulls.append(d == 0)
        vals.append(d - 1 + offsets[i])
    return vals, nulls


def packed_sort_key(packed: jax.Array, live: jax.Array) -> jax.Array:
    """Displace dead rows to the dtype max so one argsort orders live rows by
    key AND sorts dead rows last. Digits use at most 62 (int64) / 30 (int32)
    bits, so the sentinel never collides with a live key."""
    return jnp.where(live, packed, jnp.iinfo(packed.dtype).max)


def group_segments(sorted_lanes: list, sorted_nulls: list,
                   sorted_live: jax.Array):
    """Given key lanes already permuted into sorted order, return
    (segment_id per row int32, is_group_start bool). Dead rows get segment id
    pointing at a trailing dummy segment."""
    n = sorted_live.shape[0]
    differs = jnp.zeros((n - 1,), dtype=bool) if n > 1 else jnp.zeros((0,), dtype=bool)
    for lane, nl in zip(sorted_lanes, sorted_nulls):
        dval = lane[1:] != lane[:-1]
        if nl is not None:
            n1, n0 = nl[1:], nl[:-1]
            # adjacent rows differ unless both NULL or both equal non-NULL
            # (SQL GROUP BY treats NULLs as one group)
            d = (n1 != n0) | (~n1 & ~n0 & dval)
        else:
            d = dval
        differs = differs | d
    first = jnp.ones((1,), dtype=bool) if n > 0 else jnp.zeros((0,), dtype=bool)
    boundary = jnp.concatenate([first, differs | (sorted_live[1:] != sorted_live[:-1])]) \
        if n > 1 else first
    start = boundary & sorted_live
    seg = jnp.cumsum(start.astype(jnp.int32)) - 1
    seg = jnp.where(sorted_live & (seg >= 0), seg, max(n - 1, 0))
    return seg.astype(jnp.int32), start


# Below this many segments, segment reductions unroll into per-segment masked
# reductions (compare+select+reduce fuses into one memory-bound pass per
# segment) instead of XLA scatter ops: on TPU a scatter over an 8M-row lane
# costs ~300 ms while a fused masked reduction is bandwidth-bound (~1 ms), so
# for Q1-sized group counts the loop is ~100x faster. Above the threshold the
# O(nseg * N) loop loses to the O(N) scatter.
SMALL_NSEG = 64


def seg_sum(vals: jax.Array, seg: jax.Array, nseg: int) -> jax.Array:
    if nseg <= SMALL_NSEG:
        zero = jnp.zeros((), vals.dtype)
        return jnp.stack([jnp.sum(jnp.where(seg == i, vals, zero))
                          for i in range(nseg)])
    return jax.ops.segment_sum(vals, seg, num_segments=nseg)


def seg_min(vals: jax.Array, seg: jax.Array, nseg: int) -> jax.Array:
    if nseg <= SMALL_NSEG:
        hi = _ident_max(vals.dtype)
        return jnp.stack([jnp.min(jnp.where(seg == i, vals, hi))
                          for i in range(nseg)])
    return jax.ops.segment_min(vals, seg, num_segments=nseg)


def seg_max(vals: jax.Array, seg: jax.Array, nseg: int) -> jax.Array:
    if nseg <= SMALL_NSEG:
        lo = _ident_min(vals.dtype)
        return jnp.stack([jnp.max(jnp.where(seg == i, vals, lo))
                          for i in range(nseg)])
    return jax.ops.segment_max(vals, seg, num_segments=nseg)


def _ident_max(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


def _ident_min(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def compact_perm(live: jax.Array) -> jax.Array:
    """Stable permutation bringing live rows to the front."""
    return jnp.argsort(~live, stable=True)


def _gather_arrays(arrays: list, idx: jax.Array) -> list:
    """All-lane gather through the Pallas dispatch layer: one fused kernel
    materializing every output lane when the mode and shapes allow, one
    jnp.take (XLA gather) per lane otherwise."""
    from igloo_tpu.exec import dispatch
    return dispatch.gather_columns(arrays, idx)


def apply_perm(batch: DeviceBatch, perm: jax.Array) -> DeviceBatch:
    arrays = []
    for c in batch.columns:
        arrays.append(c.values)
        if c.nulls is not None:
            arrays.append(c.nulls)
    arrays.append(batch.live)
    out = _gather_arrays(arrays, perm)
    cols = []
    i = 0
    for c in batch.columns:
        vals = out[i]
        i += 1
        nulls = None
        if c.nulls is not None:
            nulls = out[i]
            i += 1
        # replace() keeps the carrier spec/arg: a row gather permutes carrier
        # lanes as happily as wide ones (bounds dropped, as before)
        cols.append(replace(c, values=vals, nulls=nulls, bounds=None))
    return DeviceBatch(batch.schema, cols, out[i])


def gather_batch(batch: DeviceBatch, idx: jax.Array,
                 valid: Optional[jax.Array] = None,
                 null_pad: bool = False) -> list[DeviceColumn]:
    """Gather rows of all columns by `idx`. When `null_pad` and valid is given,
    out-of-match rows become NULL (outer-join padding)."""
    safe = jnp.clip(idx, 0, batch.capacity - 1)
    arrays = []
    for c in batch.columns:
        arrays.append(c.values)
        if c.nulls is not None:
            arrays.append(c.nulls)
    out = _gather_arrays(arrays, safe)
    cols = []
    i = 0
    for c in batch.columns:
        vals = out[i]
        i += 1
        nulls = None
        if c.nulls is not None:
            nulls = out[i]
            i += 1
        if null_pad and valid is not None:
            pad = ~valid
            nulls = pad if nulls is None else (nulls | pad)
        cols.append(replace(c, values=vals, nulls=nulls, bounds=None))
    return cols


def compact_to(batch: DeviceBatch, capacity: int) -> DeviceBatch:
    """Compact live rows to the front AND resize to `capacity` in one step,
    slicing the permutation BEFORE the column gathers so every gather is
    output-sized. The equivalent apply_perm(compact_perm)+resize pair gathers
    every column at FULL input width first — at 8M lanes x 8 columns that is
    ~0.5s of wasted HBM traffic per compaction on a v5e (XLA does not sink the
    later slice into the gather operand). Rows past `capacity` are dropped;
    callers guarantee (or flag-check) that live count fits."""
    perm = compact_perm(batch.live)
    if capacity < perm.shape[0]:
        perm = perm[:capacity]
    cols = []
    for c in batch.columns:
        vals = jnp.take(c.values, perm)
        nulls = jnp.take(c.nulls, perm) if c.nulls is not None else None
        cols.append(replace(c, values=vals, nulls=nulls, bounds=None))
    live = jnp.take(batch.live, perm)
    if capacity > perm.shape[0]:
        return resize_batch(DeviceBatch(batch.schema, cols, live), capacity)
    return DeviceBatch(batch.schema, cols, live)


def resize_to(values: jax.Array, capacity: int, fill=0) -> jax.Array:
    n = values.shape[0]
    if n == capacity:
        return values
    if n > capacity:
        return values[:capacity]
    pad = jnp.full((capacity - n,), fill, dtype=values.dtype)
    return jnp.concatenate([values, pad])


def resize_batch(batch: DeviceBatch, capacity: int) -> DeviceBatch:
    """Change a batch's static capacity (host-decided; used for shape bucketing
    after host-synced row counts). Live rows must already be compacted when
    shrinking."""
    if capacity == batch.capacity:
        return batch
    cols = []
    for c in batch.columns:
        vals = resize_to(c.values, capacity)
        nulls = resize_to(c.nulls, capacity, fill=False) if c.nulls is not None else None
        # carrier survives a resize: the zero pad is dead lanes (masked), and
        # a zero carrier widening to the offset is still a masked lane
        cols.append(replace(c, values=vals, nulls=nulls, bounds=None))
    return DeviceBatch(batch.schema, cols, resize_to(batch.live, capacity, fill=False))
