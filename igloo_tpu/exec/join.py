"""Join kernel: two-phase sorted-probe equi-join with exact verification.

Replaces the reference's HashJoinExec (crates/engine/src/operators/hash_join.rs),
whose build side is a row-at-a-time HashMap keyed by debug-formatted strings
(:116-127) and whose probe emits 1-row batches (:165-211), with right/full outer
unmatched rows never emitted (gap G4). The TPU design:

  phase P (device): normalize keys to int64 lanes, combine to a mixed 64-bit hash,
      stable-sort the build side by hash, binary-search each probe row's hash range
      -> per-row candidate counts, total count (one scalar)
  host: one sync for the total -> choose padded output capacity (power-of-two
      bucketing keeps the compile cache small)
  phase E (device): expand candidates (prefix-sum + searchsorted inversion),
      gather both sides, verify EXACT key equality (hash collisions only waste
      padded slots, never emit wrong rows), apply the residual predicate, derive
      matched flags, and null-pad unmatched preserved-side rows for outer joins.

All join types: inner/left/right/full/cross/semi/anti (+ null-aware anti for
NOT IN). Strings join via per-entry dictionary hash lanes (128-bit effective with
the verify lane), so differently-dictionary-encoded tables join exactly.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from igloo_tpu import types as T
from igloo_tpu.exec import dispatch
from igloo_tpu.exec import kernels as K
from igloo_tpu.exec.batch import (
    DeviceBatch, DeviceColumn, round_capacity, wide_values,
)
from igloo_tpu.exec.expr_compile import Compiled, Env
from igloo_tpu.sql.ast import JoinType
from igloo_tpu.utils import tracing


@dataclass
class _KeyLanes:
    """One join key, normalized: int64 lanes feeding the hash, equality lanes
    compared exactly during verification, and the null flag."""
    hash_ints: list
    eq_lanes: list
    null: object  # Optional[jax.Array]


@dataclass
class _Probe:
    """Device results of the probe phase (phase P)."""
    perm_r: jax.Array      # build-side sort permutation
    lower: jax.Array       # [cap_l] first candidate position per probe row
    counts: jax.Array      # [cap_l] candidate count per probe row
    prefix: jax.Array      # [cap_l] exclusive prefix sum of counts
    total: jax.Array       # scalar int64
    l_lanes: list          # per-key _KeyLanes on left
    r_lanes: list          # per-key _KeyLanes on right
    # Pallas probe overflow: some probe row's equal-hash run may extend past
    # the kernel's scan window — the executor's deferred-flag protocol
    # discards the result and re-runs the exact sort path. Always False on
    # the sort path.
    ovf: jax.Array = None  # scalar bool


# pytree registration so _Probe/_KeyLanes cross jit boundaries (probe runs in one
# jitted phase, expand in another; the probe result is a pytree of arrays)
jax.tree_util.register_pytree_node(
    _KeyLanes,
    lambda k: ((k.hash_ints, k.eq_lanes, k.null), None),
    lambda aux, ch: _KeyLanes(ch[0], ch[1], ch[2]),
)
jax.tree_util.register_pytree_node(
    _Probe,
    lambda p: ((p.perm_r, p.lower, p.counts, p.prefix, p.total,
                p.l_lanes, p.r_lanes, p.ovf), None),
    lambda aux, ch: _Probe(*ch),
)


def make_key_hash_idxs(keys: list[Compiled], pool) -> list:
    """Register per-dictionary-entry hash lanes in the const pool for every
    string-typed key. The hashes feed the jitted probe as runtime data, so a
    new dictionary (new table contents) never forces a join recompile."""
    idxs = []
    for k in keys:
        if k.dtype.is_string:
            d = k.out_dict
            h1 = d.hashes.view(np.int64) if d is not None and len(d) \
                else np.zeros(1, np.int64)
            h2 = d.hashes2.view(np.int64) if d is not None and len(d) \
                else np.zeros(1, np.int64)
            idxs.append((pool.add(h1), pool.add(h2)))
        else:
            idxs.append(None)
    return idxs


def _key_lanes(batch: DeviceBatch, keys: list[Compiled], hash_idxs: list,
               consts: tuple) -> list[_KeyLanes]:
    env = Env.from_batch(batch, consts)
    out = []
    for k, hx in zip(keys, hash_idxs):
        v, nl = k.fn(env)
        if k.dtype.is_string:
            # dictionary hash lanes: equal strings -> equal lanes across tables;
            # 128-bit effective equality with the second lane
            h1, h2 = consts[hx[0]], consts[hx[1]]
            ids = jnp.clip(v, 0, h1.shape[0] - 1)
            l1, l2 = jnp.take(h1, ids), jnp.take(h2, ids)
            out.append(_KeyLanes([l1], [l1, l2], nl))
        elif k.dtype.is_float:
            vnorm, nan = K.normalize_float(v)
            out.append(_KeyLanes(K.float_hash_int_lanes(v),
                                 [vnorm, nan.astype(jnp.int32)], nl))
        else:
            lane = v.astype(jnp.int64)
            out.append(_KeyLanes([lane], [lane], nl))
    return out


def probe_phase(left: DeviceBatch, right: DeviceBatch,
                left_keys: list[Compiled], right_keys: list[Compiled],
                l_hash_idxs=None, r_hash_idxs=None, consts: tuple = (),
                probe_plan=None) -> _Probe:
    """Jit-traceable. CROSS join = empty key lists (constant key).
    `probe_plan` (dispatch.plan_probe, part of the caller's cache key)
    routes the bounds search through the Pallas hash-probe kernel: the
    combined (m+n)-lane stable sort of `_probe_bounds` is replaced by a
    bucketed window scan over the build side's sorted hash lane — which the
    phase already pays for as `perm_r` — with the kernel's overflow flag
    surfaced as `_Probe.ovf` (deferred exact re-run)."""
    cap_l, cap_r = left.capacity, right.capacity
    if l_hash_idxs is None:
        l_hash_idxs = [None] * len(left_keys)
    if r_hash_idxs is None:
        r_hash_idxs = [None] * len(right_keys)
    if left_keys:
        l_lanes = _key_lanes(left, left_keys, l_hash_idxs, consts)
        r_lanes = _key_lanes(right, right_keys, r_hash_idxs, consts)
        l_hash = K.hash_lanes([h for kl in l_lanes for h in kl.hash_ints],
                              [kl.null for kl in l_lanes
                               for _ in kl.hash_ints])
        r_hash = K.hash_lanes([h for kl in r_lanes for h in kl.hash_ints],
                              [kl.null for kl in r_lanes
                               for _ in kl.hash_ints])
        l_keynull = _any_null(l_lanes, cap_l)
        r_keynull = _any_null(r_lanes, cap_r)
        # NULL keys never equal anything: displace to side-distinct sentinels
        l_hash = jnp.where(l_keynull, np.int64(-0x0123456789ABCDEF), l_hash)
        r_hash = jnp.where(r_keynull, np.int64(0x0FEDCBA987654321), r_hash)
    else:
        l_lanes, r_lanes = [], []
        l_hash = jnp.zeros((cap_l,), dtype=jnp.int64)
        r_hash = jnp.zeros((cap_r,), dtype=jnp.int64)

    # dead build rows displaced to the max sentinel (sorted last); any accidental
    # live MAX-hash rows are rejected by exact verification
    sort_key = jnp.where(right.live, r_hash, jnp.iinfo(jnp.int64).max)
    perm_r = jnp.argsort(sort_key, stable=True)

    if probe_plan is not None and left_keys:
        sorted_hash = jnp.take(sort_key, perm_r)
        lower, upper, ovf = dispatch.probe_bounds(probe_plan, sorted_hash,
                                                  l_hash)
    else:
        lower, upper = _probe_bounds(sort_key, l_hash)
        ovf = jnp.zeros((), jnp.bool_)
    counts = jnp.where(left.live, (upper - lower).astype(jnp.int64), 0)
    prefix = jnp.cumsum(counts) - counts
    total = jnp.sum(counts)
    return _Probe(perm_r, lower, counts.astype(jnp.int32),
                  prefix.astype(jnp.int64), total, l_lanes, r_lanes, ovf)


def _probe_bounds(build_key: jax.Array, probe_key: jax.Array):
    """Per-probe-element lower/upper insertion positions in the sorted build
    multiset, with ONE combined sort, no searchsorted: on TPU a searchsorted
    over an 8M-query lane lowers to a ~23-pass gather loop (~1.5s), and the
    previous design paid one full (m+n)-lane stable sort PER bound (probe-first
    tie-break for lower, build-first for upper). This version packs the side
    tag into the key's low bit — hash bit 0 is dropped to make room (a 63-bit
    hash; collisions only add verify-rejected candidates, never wrong rows) —
    so a single stable sort orders every equal-key run probes-first:

      lower(probe at sorted pos i) = builds strictly before i
                                   = builds before the run (they all follow
                                     the run's probes)
      upper(probe at sorted pos i) = builds up to the END of its equal-key run
                                     (run end via one reverse min-scan)

    Both bounds then scatter back to the probe's original index. Net: one
    argsort + one cumsum + one scan instead of two argsorts + two cumsums."""
    m = build_key.shape[0]
    n = probe_key.shape[0]
    total = m + n
    pos = jnp.arange(total, dtype=jnp.int32)
    mask = np.int64(-2)  # ~1: drop the hash's low bit for the side tag
    keys = jnp.concatenate([probe_key & mask, (build_key & mask) | np.int64(1)])
    perm = jnp.argsort(keys, stable=True)
    sk = jnp.take(keys, perm)
    is_build = jnp.take(pos >= n, perm)
    # builds at-or-before each sorted position; probes carry "builds before"
    cb = jnp.cumsum(is_build.astype(jnp.int32))
    lower = cb - is_build.astype(jnp.int32)
    # end of each equal-key run (tag bit ignored): reverse running min over
    # run-final positions
    krun = sk | np.int64(1)
    last = jnp.concatenate([krun[1:] != krun[:-1],
                            jnp.ones((1,), dtype=bool)])
    end_idx = jax.lax.associative_scan(
        jnp.minimum, jnp.where(last, pos, jnp.int32(total)), reverse=True)
    upper = jnp.take(cb, end_idx)
    # scatter both bounds back to each probe element's original index. Build
    # elements route to the POSITIVE out-of-bounds sentinel `m + n`: negative
    # indices would WRAP (jnp normalizes them before mode="drop" applies) and
    # clobber probe slots
    orig = jnp.take(pos, perm)
    target = jnp.where(is_build, jnp.int32(total), orig)
    lo_out = jnp.zeros((n,), dtype=jnp.int32).at[target].set(
        lower, mode="drop")
    up_out = jnp.zeros((n,), dtype=jnp.int32).at[target].set(
        upper, mode="drop")
    return lo_out, up_out


def _any_null(lanes: list[_KeyLanes], cap) -> jax.Array:
    out = jnp.zeros((cap,), dtype=bool)
    for kl in lanes:
        if kl.null is not None:
            out = out | kl.null
    return out


def semi_anti_phase(left: DeviceBatch, right: DeviceBatch,
                    left_keys: list, right_keys: list,
                    lhx: list, rhx: list, anti: bool,
                    residual: Optional[Compiled] = None,
                    window: int = 2, consts: tuple = (),
                    pack_eq: Optional[tuple] = None):
    """SEMI/ANTI without candidate expansion: membership is a sorted search
    over the build side's combined key hash with EXACT verify-lane equality
    at a `window`-slot run. The expand program (scatter-max ownership +
    associative scan + full-width gathers) hangs XLA's server-side compiler
    at multi-million-lane match capacities (observed: 25+ min on TPC-H q18's
    semi at SF1); this shape is a sort + searchsorted + a handful of gathers,
    and SEMI/ANTI only ever need a per-left-row boolean anyway.

    Without a residual the window only covers hash collisions (2 slots).
    With one (EXISTS ... AND extra-condition, e.g. q21), every candidate in
    the key's duplicate run must be tested: the window widens and a
    `truncated` flag reports any left row whose run may extend past it —
    the caller re-runs exactly (deferred overflow protocol).

    `pack_eq` (kernels.plan_pair_packing, part of the caller's cache key)
    fuses the per-key exact-verify lanes into ONE packed lane per side, so
    each window slot pays one gather+compare instead of one per key.

    Returns (DeviceBatch, truncated flag)."""
    l_lanes = _key_lanes(left, left_keys, lhx, consts)
    r_lanes = _key_lanes(right, right_keys, rhx, consts)

    def combined(lanes, live):
        flat, nulls = [], []
        valid = live
        for kl in lanes:
            for ln in kl.hash_ints:
                flat.append(ln.astype(jnp.int64))
                nulls.append(kl.null)
            if kl.null is not None:
                valid = valid & ~kl.null  # null keys never equi-match
        return K.hash_lanes(flat, nulls), valid

    lh, lvalid = combined(l_lanes, left.live)
    rh, rvalid = combined(r_lanes, right.live)
    big = jnp.int64(0x7FFFFFFFFFFFFFFF)
    rmasked = jnp.where(rvalid, rh, big)
    order = jnp.argsort(rmasked)
    rsorted = jnp.take(rmasked, order)
    rv_sorted = jnp.take(rvalid, order)
    if pack_eq is not None:
        # integer-family keys only (planner-guaranteed): each key's eq_lanes
        # is its single value lane, and the union-range digits make equal
        # values share a digit across the two tables — the window loop below
        # then pays ONE gather+compare per slot instead of one per key. NULL
        # digits collide at 0, but null keys are already excluded from
        # lvalid/rvalid.
        l_eq = [K.pack_key_lane(pack_eq, [kl.eq_lanes[0] for kl in l_lanes],
                                [kl.null for kl in l_lanes], consts)]
        r_packed = K.pack_key_lane(pack_eq,
                                   [kl.eq_lanes[0] for kl in r_lanes],
                                   [kl.null for kl in r_lanes], consts)
        r_eq = [jnp.take(r_packed, order)]
    else:
        r_eq = [jnp.take(ln.astype(jnp.int64), order)
                for kl in r_lanes for ln in kl.eq_lanes]
        l_eq = [ln.astype(jnp.int64) for kl in l_lanes for ln in kl.eq_lanes]
    lo = jnp.searchsorted(rsorted, lh)
    cap_r = right.capacity
    member = jnp.zeros(left.capacity, dtype=bool)
    truncated = jnp.asarray(False)
    last_keyeq = None
    for off in range(window):
        j = jnp.clip(lo + off, 0, cap_r - 1)
        keyeq = jnp.take(rv_sorted, j)
        for le, re_ in zip(l_eq, r_eq):
            keyeq = keyeq & (le == jnp.take(re_, j))
        ok = keyeq
        if residual is not None:
            ridx = jnp.take(order, j)
            # residual reads VALUES: widen resident carriers in-trace (fused)
            r_vals = [jnp.take(wide_values(c), ridx) for c in right.columns]
            r_nulls = [jnp.take(c.nulls, ridx) if c.nulls is not None
                       else None for c in right.columns]
            env = Env([wide_values(c) for c in left.columns] + r_vals,
                      [c.nulls for c in left.columns] + r_nulls, consts)
            rv, rn = residual.fn(env)
            ok = ok & rv
            if rn is not None:
                ok = ok & ~rn
        member = member | ok
        last_keyeq = keyeq
    if residual is not None and last_keyeq is not None:
        # a key-equal candidate at the FINAL slot means the duplicate run may
        # continue beyond the window for that row: unverified candidates
        # could flip membership — flag for an exact re-run
        # rows NOT yet matched whose run may continue: more candidates could
        # flip them to matched (changing SEMI keeps and ANTI drops alike)
        truncated = jnp.any(last_keyeq & lvalid & left.live & ~member)
    member = member & lvalid
    keep = left.live & (~member if anti else member)
    return DeviceBatch(left.schema, left.columns, keep), truncated


def expand_phase(left: DeviceBatch, right: DeviceBatch, p: _Probe,
                 match_cap: int, join_type: JoinType,
                 residual: Optional[Compiled],
                 out_schema: T.Schema, consts: tuple = (),
                 match_plan=None):
    """Jit-traceable (match_cap static). Builds the output batch.

    `match_plan` (dispatch.plan_match, part of the caller's cache key)
    routes slot-ownership materialization — the owner-scatter +
    associative-scan chain below — through the Pallas match kernel (route
    "kernel": one blocked pass with a bounded per-row window, overflow
    deferred) or a searchsorted inversion (route "search": exact, the
    algorithmic fast path for the non-Pallas tier). With a plan the return
    value is ``(batch, match_ovf)`` — the aggregate_batch conditional-tuple
    convention; route "search" never overflows."""
    cap_l = left.capacity

    # --- candidate expansion: slot j -> (probe row, j-th candidate) ---
    j = jnp.arange(match_cap, dtype=jnp.int64)
    match_ovf = None
    if match_plan is not None and match_plan[1] == "kernel":
        owner, match_ovf = dispatch.match_table(match_plan, p.prefix,
                                                p.counts, match_cap)
        probe_idx = jnp.clip(owner, 0, cap_l - 1)
    elif match_plan is not None:
        # route "search": the prefix lane is sorted (cumsum), so the owner of
        # slot j is the LAST row whose start is <= j — zero-count rows share
        # their successor's start and lose the right-insertion tie to the
        # true owner; stragglers die on the offset bound below
        match_ovf = jnp.zeros((), jnp.bool_)
        probe_idx = jnp.clip(
            jnp.searchsorted(p.prefix, j, side="right").astype(jnp.int32) - 1,
            0, cap_l - 1)
    else:
        # probe row owning each slot: scatter each row's index at its start
        # slot, then a running max fills its run. (a searchsorted over the
        # 8M-lane prefix costs ~1.5s on TPU — a 23-pass gather loop — vs
        # ~0.3s for scatter+cummax; zero-count rows share their successor's
        # start slot and lose the scatter-max tie to the true owner, which
        # has the larger index)
        starts = jnp.clip(p.prefix, 0, match_cap - 1).astype(jnp.int32)
        row_ids = jnp.arange(cap_l, dtype=jnp.int32)
        owner = jnp.zeros((match_cap,), dtype=jnp.int32).at[starts].max(
            jnp.where(p.counts > 0, row_ids, 0), mode="drop")
        probe_idx = jax.lax.associative_scan(jnp.maximum, owner)
        probe_idx = jnp.clip(probe_idx, 0, cap_l - 1)
    in_range = j < p.total
    offset = (j - jnp.take(p.prefix, probe_idx)).astype(jnp.int32)
    # rows with count 0 can be hit when prefix repeats; reject by offset bound
    cnt = jnp.take(p.counts, probe_idx)
    in_range = in_range & (offset >= 0) & (offset < cnt)
    r_pos = jnp.take(p.lower, probe_idx) + offset
    r_idx = jnp.take(p.perm_r, jnp.clip(r_pos, 0, right.capacity - 1))

    # --- exact verification (hash collisions die here, never in the output) ---
    ok = in_range & jnp.take(left.live, probe_idx) & jnp.take(right.live, r_idx)
    for lk, rk in zip(p.l_lanes, p.r_lanes):
        for llane, rlane in zip(lk.eq_lanes, rk.eq_lanes):
            ok = ok & (jnp.take(llane, probe_idx) == jnp.take(rlane, r_idx))
        if lk.null is not None:
            ok = ok & ~jnp.take(lk.null, probe_idx)
        if rk.null is not None:
            ok = ok & ~jnp.take(rk.null, r_idx)

    # --- gather both sides once (the residual env and the output columns
    # share the same indices; SEMI/ANTI never read these and XLA prunes the
    # dead gathers from their traces) ---
    l_cols = K.gather_batch(left, probe_idx)
    r_cols = K.gather_batch(right, r_idx)

    # --- residual predicate over combined row ---
    if residual is not None:
        env = Env([wide_values(c) for c in l_cols + r_cols],
                  [c.nulls for c in l_cols] + [c.nulls for c in r_cols], consts)
        rv, rn = residual.fn(env)
        ok = ok & rv & (~rn if rn is not None else True)

    # --- matched flags, computed only for the join types that read them (a
    # TPU scatter over a full lane costs ~300ms; INNER needs neither flag) ---
    l_matched = r_matched = None
    if join_type in (JoinType.LEFT, JoinType.FULL, JoinType.SEMI,
                     JoinType.ANTI):
        # probe_idx is NONDECREASING (slots for one probe row are contiguous
        # by construction), so "row i has a verified match" is a cumsum range
        # query — gathers only, no scatter:
        #   matched[i] = cumsum(ok)[prefix[i] + counts[i] - 1] - cumsum(ok)[prefix[i] - 1] > 0
        c = jnp.cumsum(ok.astype(jnp.int64))
        hi = p.prefix + p.counts.astype(jnp.int64)  # exclusive end slot
        hi_idx = jnp.clip(hi - 1, 0, match_cap - 1).astype(jnp.int32)
        lo = p.prefix
        c_before = jnp.where(lo > 0,
                             jnp.take(c, jnp.clip(lo - 1, 0,
                                                  match_cap - 1).astype(jnp.int32)),
                             jnp.int64(0))
        in_cap = hi <= match_cap  # overflowed rows handled by the re-run
        l_matched = in_cap & (p.counts > 0) & \
            ((jnp.take(c, hi_idx) - c_before) > 0)
    if join_type in (JoinType.RIGHT, JoinType.FULL):
        # build side order is arbitrary -> keep the scatter (rare join types)
        ok32 = ok.astype(jnp.int32)
        r_matched = jnp.zeros((right.capacity,), dtype=jnp.int32) \
            .at[r_idx].max(ok32, mode="drop") > 0

    def _ret(b):
        return b if match_plan is None else (b, match_ovf)

    if join_type is JoinType.SEMI:
        return _ret(DeviceBatch(out_schema, left.columns,
                                left.live & l_matched))
    if join_type is JoinType.ANTI:
        # NOT IN null semantics live in the binder-built residual (binder.py
        # _rewrite_in_subquery), not here — plain anti is correct as-is
        return _ret(DeviceBatch(out_schema, left.columns,
                                left.live & ~l_matched))

    # --- inner part: verified expanded rows, NOT compacted (live rows stay
    # mask-scattered across the match_cap slots; every downstream operator is
    # selection-mask aware, and the compaction here was a full match_cap-wide
    # argsort per join, ~1s at SF1) ---
    parts_cols = [l_cols + r_cols]
    parts_live = [ok]

    if join_type in (JoinType.LEFT, JoinType.FULL):
        lm = left.live & ~l_matched
        lperm = K.compact_perm(lm)
        lu_live = jnp.take(lm, lperm)
        lu_cols = K.gather_batch(left, lperm)
        pad_r = _null_cols(right, left.capacity)
        parts_cols.append(lu_cols + pad_r)
        parts_live.append(lu_live)
    if join_type in (JoinType.RIGHT, JoinType.FULL):
        rm = right.live & ~r_matched
        rperm = K.compact_perm(rm)
        ru_live = jnp.take(rm, rperm)
        ru_cols = K.gather_batch(right, rperm)
        pad_l = _null_cols(left, right.capacity)
        parts_cols.append(pad_l + ru_cols)
        parts_live.append(ru_live)

    # concatenate parts (static shapes: match_cap + cap_l? + cap_r?)
    n_cols = len(parts_cols[0])
    out_cols = []
    for ci in range(n_cols):
        vals = jnp.concatenate([pc[ci].values for pc in parts_cols])
        any_nulls = any(pc[ci].nulls is not None for pc in parts_cols)
        if any_nulls:
            nulls = jnp.concatenate([
                pc[ci].nulls if pc[ci].nulls is not None
                else jnp.zeros((pc[ci].values.shape[0],), dtype=bool)
                for pc in parts_cols])
        else:
            nulls = None
        proto = parts_cols[0][ci]
        # per-column carriers are consistent across parts (every part of a
        # column gathers — or null-pads in carrier dtype — from the same
        # source batch), so the concat output keeps the proto's spec/arg
        out_cols.append(replace(proto, values=vals, nulls=nulls, bounds=None))
    out_live = jnp.concatenate(parts_live)
    if len(parts_live) > 1:
        # outer joins: compact the concatenated parts into contiguous rows.
        # Inner joins skip this — their single part stays MASK-SCATTERED (see
        # above; anything that later needs compaction, e.g. resize_batch,
        # must compact first) and the argsort here costs a ~2M-lane sort
        perm = K.compact_perm(out_live)
        out_cols = [replace(c, values=jnp.take(c.values, perm),
                            nulls=jnp.take(c.nulls, perm)
                            if c.nulls is not None else None)
                    for c in out_cols]
        out_live = jnp.take(out_live, perm)
    return _ret(DeviceBatch(out_schema, out_cols, out_live))


def _null_cols(batch: DeviceBatch, cap: int) -> list[DeviceColumn]:
    cols = []
    for c in batch.columns:
        # zeros in the CARRIER dtype (concat parts must agree); an offset
        # carrier widens pad zeros to its offset, but every pad lane is null
        # here — masked at output, bit-identical
        vals = jnp.zeros((cap,), dtype=c.values.dtype)
        cols.append(replace(c, values=vals,
                            nulls=jnp.ones((cap,), dtype=bool), bounds=None))
    return cols


def choose_match_capacity(total: int) -> int:
    return round_capacity(max(int(total), 1))


# ---------------------------------------------------------------------------
# Direct "array join": the fast path for dense-integer-key PK-FK joins (all of
# TPC-H). When one side's single join key is an integer whose host-known value
# bounds (DeviceColumn.bounds, computed at scan time) span a small dense range,
# that side becomes the BUILD side of a positional table: one scatter writes
# build row ids at slot (key - lo), and each probe row finds its unique match
# with one gather — no hashing, no sorting. This replaces the sorted-probe
# path's 2-3 large stable sorts (~1s at SF1 Q3) with one scatter + one gather
# (~20ms). Correctness does NOT depend on the uniqueness guess: a slot-count
# check sets a deferred flag when build keys collide, and the executor re-runs
# the plan through the exact sorted-probe path (same mechanism as speculative
# capacity overflow). Key equality is exact BY CONSTRUCTION (slot index = key),
# so there is no verify phase at all.
# ---------------------------------------------------------------------------

# widest positional table we will allocate (lanes; int32 => 64 MiB at the cap)
DIRECT_RANGE_BUDGET = 1 << 24


def _direct_key_ok(c: Compiled) -> bool:
    return c.dtype.is_integer or c.dtype.id == T.TypeId.DATE32


def choose_direct_build(lks: list, rks: list, left_cap: int,
                        right_cap: int, join_type: JoinType,
                        banned: frozenset = frozenset()):
    """Pick the build side + key for a direct join, or None when inapplicable.
    Returns (side, (base, table_size), key_idx) with side in {"left",
    "right"}; (base, table_size) is the CANONICAL positional table
    (exec/capacity.canonical_direct_table) — size quantized to the capacity
    family and base grid-aligned, so the raw key bounds never become program
    constants and neighboring scale factors share one compiled join. A
    (side, key) qualifies when the key's bounds span <= DIRECT_RANGE_BUDGET
    and the side's row capacity could plausibly be unique over that range
    (cap <= its canonical table size: any padded batch whose live rows fit
    the range fits the table, whatever the family's padding ratio or
    hysteresis — a looser-than-exact test whose wrong picks the runtime
    duplicate flag repairs and negative-caches); among qualifiers the
    smaller side wins (PK side in every FK join). Remaining key
    pairs become post-gather equality checks, so every key must be
    integer-family. The runtime duplicate check backstops a wrong pick;
    `banned` carries sides that PROVED duplicated on earlier runs (the
    ("nodirect", jfp_core, side) negative cache), so the other side still
    gets its chance."""
    from igloo_tpu.exec.capacity import canonical_direct_table
    if join_type is JoinType.CROSS or not lks:
        return None
    if not all(_direct_key_ok(c) for c in lks + rks):
        return None
    options = []
    for side, keys, cap in (("right", rks, right_cap), ("left", lks, left_cap)):
        if side in banned:
            continue
        for i, key in enumerate(keys):
            b = key.out_bounds
            if b is None:
                continue
            rng = int(b[1]) - int(b[0]) + 1
            if rng > DIRECT_RANGE_BUDGET:
                continue
            base, tsize = canonical_direct_table(int(b[0]), int(b[1]))
            if cap <= tsize <= DIRECT_RANGE_BUDGET:
                options.append((cap, rng, side, (base, tsize), i))
    if not options:
        tracing.counter("join.direct_ineligible")
        return None
    options.sort(key=lambda o: (o[0], o[1], o[2], o[4]))
    _, _, side, table, idx = options[0]
    tracing.counter("join.direct_eligible")
    return side, table, idx


def direct_probe(probe: DeviceBatch, build: DeviceBatch,
                 probe_key: Compiled, build_key: Compiled,
                 lo: int, table_size: int, swapped: bool,
                 residual: Optional[Compiled], consts: tuple,
                 extra_keys: Sequence = ()):
    """Probe half of the direct array join, jit-traceable: build the
    positional table (one scatter), probe it (one gather), verify extra key
    pairs and the residual. Returns (ok, safe_bidx, dup) WITHOUT
    materializing any output columns — callers gather lazily (the fused
    compiler compacts first; XLA prunes residual gathers of unread columns).
    `dup` is a device bool: True iff two valid build rows shared a slot
    (result must be discarded and the plan re-run on the exact path)."""
    bcap = build.capacity
    bkey, bnull = build_key.fn(Env.from_batch(build, consts))
    valid_b = build.live if bnull is None else (build.live & ~bnull)
    slot = bkey.astype(jnp.int64) - lo
    in_rng = (slot >= 0) & (slot < table_size)
    valid_b = valid_b & in_rng
    # invalid rows displace to the out-of-bounds slot -> dropped by the scatter
    slot = jnp.where(valid_b, slot, table_size).astype(jnp.int32)
    row_ids = jnp.arange(bcap, dtype=jnp.int32)
    table = jnp.full((table_size,), -1, jnp.int32).at[slot].max(
        row_ids, mode="drop")
    # duplicate build keys: two rows target one slot -> fewer filled slots
    # than valid rows. One O(table_size) reduction, no second scatter.
    dup = jnp.sum((table >= 0).astype(jnp.int64)) < \
        jnp.sum(valid_b.astype(jnp.int64))

    pkey, pnull = probe_key.fn(Env.from_batch(probe, consts))
    pslot = pkey.astype(jnp.int64) - lo
    p_ok = (pslot >= 0) & (pslot < table_size) & probe.live
    if pnull is not None:
        p_ok = p_ok & ~pnull
    bidx = jnp.take(table, jnp.clip(pslot, 0, table_size - 1).astype(jnp.int32))
    ok = p_ok & (bidx >= 0)
    safe_bidx = jnp.clip(bidx, 0, bcap - 1)
    ok = verify_extra_keys(ok, probe, build, safe_bidx, extra_keys, consts)
    if residual is not None:
        b_cols = K.gather_batch(build, safe_bidx)
        p_cols = list(probe.columns)
        l_cols, r_cols = (b_cols, p_cols) if swapped else (p_cols, b_cols)
        env = Env([wide_values(c) for c in l_cols + r_cols],
                  [c.nulls for c in l_cols] + [c.nulls for c in r_cols],
                  consts)
        rv, rn = residual.fn(env)
        ok = ok & rv & (~rn if rn is not None else True)
    return ok, safe_bidx, dup


def direct_join_phase(probe: DeviceBatch, build: DeviceBatch,
                      probe_key: Compiled, build_key: Compiled,
                      lo: int, table_size: int, swapped: bool,
                      join_type: JoinType, residual: Optional[Compiled],
                      out_schema: T.Schema, consts: tuple = (),
                      extra_keys: Sequence = ()):
    """Jit-traceable single-pass direct join. `swapped` means the plan's LEFT
    input is the build side (probe = plan right). `extra_keys` are further
    (probe key, build key) equi-pairs of a multi-key join, verified by exact
    equality after the gather (the positional table handles one key; a
    duplicate under that key alone still raises `dup`, so multi-key uniqueness
    is never assumed). Returns (DeviceBatch, dup)."""
    jt = join_type
    bcap, pcap = build.capacity, probe.capacity
    ok, safe_bidx, dup = direct_probe(probe, build, probe_key, build_key,
                                      lo, table_size, swapped, residual,
                                      consts, extra_keys)
    b_cols = K.gather_batch(build, safe_bidx)
    p_cols = [replace(c, bounds=None) for c in probe.columns]
    l_cols, r_cols = (b_cols, p_cols) if swapped else (p_cols, b_cols)

    # which original side is preserved / reduced to a mask
    probe_is_left = not swapped
    if jt in (JoinType.SEMI, JoinType.ANTI):
        if probe_is_left:
            keep = probe.live & ok if jt is JoinType.SEMI else probe.live & ~ok
            return DeviceBatch(out_schema, probe.columns, keep), dup
        matched = _build_matched(ok, safe_bidx, bcap)
        keep = build.live & matched if jt is JoinType.SEMI \
            else build.live & ~matched
        return DeviceBatch(out_schema, build.columns, keep), dup

    probe_preserved = (jt is JoinType.FULL
                       or (jt is JoinType.LEFT and probe_is_left)
                       or (jt is JoinType.RIGHT and not probe_is_left))
    build_preserved = (jt is JoinType.FULL
                       or (jt is JoinType.LEFT and not probe_is_left)
                       or (jt is JoinType.RIGHT and probe_is_left))

    if probe_preserved:
        # unmatched probe rows stay inline with a null-padded build side
        main_live = probe.live
        pad = ~ok
        b_cols = [replace(c, nulls=pad if c.nulls is None
                          else (c.nulls | pad)) for c in b_cols]
        l_cols, r_cols = (b_cols, p_cols) if swapped else (p_cols, b_cols)
    else:
        main_live = ok

    parts_cols = [l_cols + r_cols]
    parts_live = [main_live]
    if build_preserved:
        matched = _build_matched(ok, safe_bidx, bcap)
        um = build.live & ~matched
        uperm = K.compact_perm(um)
        u_live = jnp.take(um, uperm)
        u_cols = K.gather_batch(build, uperm)
        pad_cols = _null_cols(probe, bcap)
        parts_cols.append((u_cols + pad_cols) if swapped
                          else (pad_cols + u_cols))
        parts_live.append(u_live)

    if len(parts_cols) == 1:
        return DeviceBatch(out_schema, parts_cols[0], parts_live[0]), dup
    out_cols = []
    for ci in range(len(parts_cols[0])):
        vals = jnp.concatenate([pc[ci].values for pc in parts_cols])
        any_nulls = any(pc[ci].nulls is not None for pc in parts_cols)
        if any_nulls:
            nulls = jnp.concatenate([
                pc[ci].nulls if pc[ci].nulls is not None
                else jnp.zeros((pc[ci].values.shape[0],), dtype=bool)
                for pc in parts_cols])
        else:
            nulls = None
        proto = parts_cols[0][ci]
        # per-column carriers are consistent across parts (every part of a
        # column gathers — or null-pads in carrier dtype — from the same
        # source batch), so the concat output keeps the proto's spec/arg
        out_cols.append(replace(proto, values=vals, nulls=nulls, bounds=None))
    out_live = jnp.concatenate(parts_live)
    return DeviceBatch(out_schema, out_cols, out_live), dup


def verify_extra_keys(ok: jax.Array, probe: DeviceBatch, build: DeviceBatch,
                      safe_bidx: jax.Array, extra_keys, consts) -> jax.Array:
    """Fold the remaining equi-key pairs of a multi-key direct join into the
    match mask: exact integer equality, SQL null semantics (NULL matches
    nothing)."""
    for pk_c, bk_c in extra_keys:
        pv, pn = pk_c.fn(Env.from_batch(probe, consts))
        bv, bn = bk_c.fn(Env.from_batch(build, consts))
        ok = ok & (pv.astype(jnp.int64) ==
                   jnp.take(bv, safe_bidx).astype(jnp.int64))
        if pn is not None:
            ok = ok & ~pn
        if bn is not None:
            ok = ok & ~jnp.take(bn, safe_bidx)
    return ok


def _build_matched(ok: jax.Array, safe_bidx: jax.Array, bcap: int) -> jax.Array:
    """Per-build-row matched flag: scatter-max of ok at each probe's match."""
    tgt = jnp.where(ok, safe_bidx, bcap)
    return jnp.zeros((bcap,), jnp.int32).at[tgt].max(
        ok.astype(jnp.int32), mode="drop") > 0


def join_batches(left: DeviceBatch, right: DeviceBatch,
                 left_keys: list[Compiled], right_keys: list[Compiled],
                 join_type: JoinType, residual: Optional[Compiled],
                 out_schema: T.Schema,
                 probe_jit: Optional[Callable] = None,
                 expand_jit: Optional[Callable] = None,
                 pool=None) -> DeviceBatch:
    """Host-side driver: probe (device) -> one host sync for the candidate count
    -> expand (device). `probe_jit`/`expand_jit` let the executor pass cached
    jax.jit-wrapped phases; defaults run them eagerly. `pool` must be the
    ConstPool the keys/residual were compiled against (a fresh one otherwise);
    key hash lanes are registered into it."""
    from igloo_tpu.exec.expr_compile import ConstPool
    if join_type is JoinType.CROSS:
        left_keys, right_keys = [], []
    if pool is None:
        pool = ConstPool()
    lhx = make_key_hash_idxs(left_keys, pool)
    rhx = make_key_hash_idxs(right_keys, pool)
    consts = pool.device_args()
    pf = probe_jit or (lambda l, r, c: probe_phase(
        l, r, left_keys, right_keys, lhx, rhx, c))
    ef = expand_jit or (lambda l, r, p, mc, c: expand_phase(
        l, r, p, mc, join_type, residual, out_schema, c))
    p = pf(left, right, consts)
    total = int(p.total)  # the one host sync
    match_cap = choose_match_capacity(total)
    return ef(left, right, p, match_cap, consts)
