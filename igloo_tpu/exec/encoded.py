"""Self-describing carrier encoding for HOST-side Arrow tables.

exec/codec.py narrows columns at the host->HBM boundary; this module applies
the same carrier algebra to the engine's *Arrow* boundaries — cross-worker
exchange buckets (cluster/exchange.py) and GRACE partition buffers
(exec/grace.py) — so shipped and buffered bytes scale with carrier width, not
engine-lane width (docs/compressed_execution.md):

- integer-family columns (int64/int32/date32/timestamp[us]) offset-shrink to
  int8/int16/int32 when the value RANGE fits (exactly codec._shrink_int);
- float64 columns ride scaled-decimal int carriers or exact float32 when the
  host proves losslessness (exactly codec._shrink_float, including the
  on-device divide canary gate);
- string columns dictionary-encode ONCE per input table, so every bucket
  slice of a partitioned result shares one unified dictionary instead of
  rebuilding (and re-shipping) a dictionary per record batch.

The encoding is self-describing: each encoded field carries a
``igloo_enc`` metadata JSON naming the original lane and the widen payload,
so `decode_table` needs no side channel and is a no-op on plain tables.
Null masks stay ordinary Arrow validity — null_count survives encoding.

Two-phase API for exchange (hash-routing must see LOGICAL values — an
offset carrier would send equal keys of the two join sides to different
buckets): `encode_strings` first (dictionary ids hash by dictionary VALUE,
so routing is unaffected), partition, then `apply_numeric` per bucket slice
with ONE `plan_numeric` spec computed on the whole input (every bucket gets
the identical encoded schema). GRACE buckets never co-hash across tables
after partitioning, so `encode_table` does plan+apply in one step there.
"""
from __future__ import annotations

import json
from typing import Optional

import numpy as np
import pyarrow as pa

from igloo_tpu.exec import codec

META_KEY = b"igloo_enc"

_LANE_TO_ARROW = {
    "int64": pa.int64(), "int32": pa.int32(),
    "float64": pa.float64(), "float32": pa.float32(),
    "date32": pa.date32(), "timestamp[us]": pa.timestamp("us"),
    "string": pa.string(), "large_string": pa.large_string(),
}

#: lanes whose carrier rides an integer numpy lane (what _shrink_int sees)
_INT_NP_LANE = {"int64": np.int64, "int32": np.int32,
                "date32": np.int32, "timestamp[us]": np.int64}


def _lane_code(t: pa.DataType) -> Optional[str]:
    for code, at in _LANE_TO_ARROW.items():
        if t.equals(at):
            return code
    return None


def field_spec(f: pa.Field) -> Optional[dict]:
    """The decoded ``igloo_enc`` spec of a field, or None when unencoded."""
    md = f.metadata
    if not md or META_KEY not in md:
        return None
    return json.loads(md[META_KEY].decode())


def is_encoded(table: pa.Table) -> bool:
    return any(f.metadata and META_KEY in f.metadata for f in table.schema)


def _combined(col):
    return col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col


def _tagged(name: str, typ: pa.DataType, nullable: bool, spec: dict) -> pa.Field:
    return pa.field(name, typ, nullable,
                    metadata={META_KEY: json.dumps(spec).encode()})


# --- strings -----------------------------------------------------------------


def encode_strings(table: pa.Table) -> pa.Table:
    """Dictionary-encode every string column ONCE for the whole input. All
    later zero-copy slices/batches of the result share the single unified
    dictionary — Arrow IPC then ships it once per stream instead of once per
    record batch."""
    if not codec.encoded_enabled():
        return table
    for i, f in enumerate(table.schema):
        code = _lane_code(f.type)
        if code not in ("string", "large_string"):
            continue
        arr = _combined(table.column(i))
        if not pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_encode()
        table = table.set_column(
            i, _tagged(f.name, arr.type, f.nullable, {"lane": code}), arr)
    return table


# --- numerics ----------------------------------------------------------------


def plan_numeric(table: pa.Table) -> dict:
    """{column name: spec} for every numeric column that provably shrinks,
    computed over the WHOLE table so every slice encoded with this plan gets
    an identical schema. spec fields: lane (original arrow lane code), to
    (carrier numpy dtype name), and off | scale | f32."""
    if not codec.encoded_enabled() or table.num_rows == 0:
        return {}
    out: dict = {}
    for f in table.schema:
        if f.metadata and META_KEY in f.metadata:
            continue
        code = _lane_code(f.type)
        if code in _INT_NP_LANE:
            lane = np.dtype(_INT_NP_LANE[code])
            arr = _combined(table.column(f.name))
            v = _int_values(arr, lane)
            if v is None:
                continue
            shrunk = codec._shrink_int(v, lane)
            if shrunk is None or shrunk[0].dtype.itemsize >= lane.itemsize:
                continue
            out[f.name] = {"lane": code, "to": shrunk[0].dtype.name,
                           "off": shrunk[1].offset}
        elif code == "float64":
            arr = _combined(table.column(f.name))
            v = np.asarray(arr.cast(pa.float64()).fill_null(0.0),
                           dtype=np.float64)
            if v.size == 0:
                continue
            shrunk = codec.shrink(v, np.dtype(np.float64))
            if shrunk is None:
                continue
            carrier, spec = shrunk
            if carrier.dtype.itemsize >= 8:
                continue
            if spec.scale != 1.0 or carrier.dtype.kind == "i":
                # scaled-decimal (scale may be 1.0: integral floats). NOTE an
                # int carrier with an offset would not survive a per-slice
                # re-derivation; bake the global offset in
                out[f.name] = {"lane": code, "to": carrier.dtype.name,
                               "scale": spec.scale, "off": spec.offset}
            else:
                out[f.name] = {"lane": code, "to": "float32", "f32": True}
    return out


def _int_values(arr: pa.Array, lane: np.dtype) -> Optional[np.ndarray]:
    """Null-safe int lane values (nulls filled with the non-null MIN so the
    fill cannot widen the range); None when empty or all-null."""
    import pyarrow.compute as pc
    if len(arr) == 0 or arr.null_count == len(arr):
        return None
    arr = arr.cast(pa.from_numpy_dtype(lane))
    if arr.null_count:
        arr = pc.fill_null(arr, pc.min(arr))
    return np.asarray(arr).astype(lane, copy=False)


def apply_numeric(table: pa.Table, plan: dict) -> pa.Table:
    """Encode `table`'s columns per a `plan_numeric` spec (deterministic: two
    slices encoded with one plan get identical schemas)."""
    if not plan:
        return table
    import pyarrow.compute as pc
    for i, f in enumerate(table.schema):
        spec = plan.get(f.name)
        if spec is None:
            continue
        arr = _combined(table.column(i))
        mask = np.asarray(arr.is_null()) if arr.null_count else None
        to = np.dtype(spec["to"])
        if spec.get("f32"):
            c = np.asarray(arr.fill_null(0.0), dtype=np.float64) \
                .astype(np.float32)
        elif "scale" in spec:
            v = np.asarray(arr.cast(pa.float64()).fill_null(0.0),
                           dtype=np.float64)
            c = (np.rint(v * spec["scale"]).astype(np.int64)
                 - int(spec.get("off", 0))).astype(to)
        else:
            lane = np.dtype(_INT_NP_LANE[spec["lane"]])
            off = int(spec["off"])
            filled = pc.fill_null(arr.cast(pa.from_numpy_dtype(lane)), off)
            v = np.asarray(filled).astype(lane, copy=False)
            c = (v.astype(np.int64) - off).astype(to)
        out = pa.array(c, mask=mask)
        table = table.set_column(
            i, _tagged(f.name, out.type, f.nullable, spec), out)
    return table


def encode_table(table: pa.Table, strings: bool = False) -> pa.Table:
    """One-shot plan+apply for a table that is never co-hashed with another
    (GRACE partition buffers): per-table specs are safe there because every
    bucket decodes back to the identical logical schema before executing."""
    if not codec.encoded_enabled():
        return table
    if strings:
        table = encode_strings(table)
    return apply_numeric(table, plan_numeric(table))


# --- decode ------------------------------------------------------------------


def decode_table(table: pa.Table) -> pa.Table:
    """Inverse of the encoders, driven entirely by field metadata; a no-op on
    plain tables. Bit-identical: integer widen is exact addition, the
    scaled-decimal divide replays the host-verified IEEE-f64 division, f32
    upcast is exact."""
    if not is_encoded(table):
        return table
    for i, f in enumerate(table.schema):
        spec = field_spec(f)
        if spec is None:
            continue
        lane_t = _LANE_TO_ARROW[spec["lane"]]
        arr = _combined(table.column(i))
        if spec["lane"] in ("string", "large_string"):
            out = arr.cast(lane_t)
        else:
            mask = np.asarray(arr.is_null()) if arr.null_count else None
            v = np.asarray(arr.fill_null(0))
            if "scale" in spec:
                wide = (v.astype(np.int64) + int(spec.get("off", 0))) \
                    .astype(np.float64) / np.float64(spec["scale"])
                out = pa.array(wide, mask=mask)
            elif spec.get("f32"):
                out = pa.array(v.astype(np.float64), mask=mask)
            else:
                lane = np.dtype(_INT_NP_LANE[spec["lane"]])
                wide = (v.astype(np.int64) + int(spec["off"])).astype(lane)
                out = pa.array(wide, mask=mask).cast(lane_t)
        table = table.set_column(
            i, pa.field(f.name, out.type, f.nullable), out)
    return table


def column_min_max(table: pa.Table, name: str) -> Optional[tuple]:
    """LOGICAL (lo, hi) ints of an integer-family column, decoding carrier
    metadata instead of the values (GRACE union bounds over encoded
    buckets). None when empty or all-null."""
    import pyarrow.compute as pc
    if table.num_rows == 0:
        return None
    col = table.column(name)
    mm = pc.min_max(col)
    if not mm["min"].is_valid:
        return None
    spec = field_spec(table.schema.field(name))
    if spec is not None:
        return (int(mm["min"].as_py()) + int(spec["off"]),
                int(mm["max"].as_py()) + int(spec["off"]))
    t = col.type
    if pa.types.is_date(t) or pa.types.is_timestamp(t):
        return int(mm["min"].value), int(mm["max"].value)
    return int(mm["min"].as_py()), int(mm["max"].as_py())
