"""Pallas kernel dispatch: the ONE gateway to ``exec/pallas_kernels.py``.

Every adoption site (join probe, sort-tier aggregation, batch gathers)
consults this module at PLAN/TRACE time — a host-side static decision that
callers fold into their jit cache keys (``cache_token()`` rides every
``Executor._jitted`` key and the fused program key, per-kernel plans ride
the per-op fingerprints) — and routes through the ``probe_bounds`` /
``segagg`` / ``gather_columns`` wrappers below, which are the only legal
callers of ``pallas_kernels`` (igloo-lint ``pallas-dispatch`` rule: the
flag and the fallback ladder must not be bypassable).

Knob: ``IGLOO_TPU_PALLAS``
  - ``auto`` (default)  kernels on TPU backends only, compiled;
  - ``0``               kernels off everywhere — reproduces the sort-path
                        plans and results bit-identically;
  - ``1``               kernels on; on non-TPU backends this implies the
                        Pallas interpreter (a compiled Pallas call needs
                        Mosaic/TPU);
  - ``interpret``       kernels on through the Pallas interpreter on any
                        backend — the CPU equivalence mode tier-1 uses.

Fallback ladder (each rung attributable): mode off / non-TPU auto -> sort
path silently; eligibility miss or an earlier failure's negative cache ->
sort path + ``pallas.fallback.<reason>``; COMPILE failure (a program the
backend cannot lower) -> caught at the executor's call sites, negative
cache + sort-path re-run (``pallas.compile_fallback``); runtime overflow
(probe window / agg table) -> deferred flag -> sort-path re-run +
negative cache (``pallas.probe_overflow`` / ``pallas.agg_overflow``).

Block shapes and table sizes derive from the canonical capacity families
(exec/capacity.py): lane capacities are family members (powers of two), so
``pow2_block`` blocks always divide them and kernel programs are keyed by
the same small shape family as the rest of the engine.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from igloo_tpu.exec.capacity import canonical_capacity, pow2_block
from igloo_tpu.utils import tracing

#: empty-slot sentinel in the hash-agg key table (canonical definition —
#: the kernels module imports it from here); packed key lanes are
#: mixed-radix digit strings and therefore always >= 0
EMPTY_KEY = np.int64(-1)

# --- kernel-eligibility bounds --------------------------------------------

#: per-probe-row bucket scan window (bounded ragged emission); a build-side
#: duplicate-key run longer than this overflows to the sort path
PROBE_WINDOW = 16
#: probe rows per grid block
PROBE_BLOCK = 1024
#: expected bucket occupancy target: buckets = build_capacity >> this
PROBE_BUCKET_SHIFT = 3
#: widest build side the probe kernel accepts in INTERPRET mode (the sorted
#: hash lane must be kernel-resident); matches the speculative-join budget
PROBE_MAX_BUILD = 1 << 22
#: compiled-mode clamp: the resident int64 hash lane must fit VMEM
#: (~16 MB/core) beside the bucket-starts lane and the probe blocks —
#: 2^20 lanes = 8 MB. A compile failure IS caught (the executor's
#: compile-failure rung), but it costs a wasted compile and permanently
#: bans the op, so the compiled bounds stay conservative.
PROBE_MAX_BUILD_COMPILED = 1 << 20
#: bucket-count clamp (the starts lane is a kernel input)
PROBE_MAX_BUCKETS = 1 << 19

#: the direct-scatter aggregate's "small segment space" bound: at or under
#: this many segments exec/aggregate.py scatters unconditionally; above it
#: the scatter path needs a tight aggregate budget and the Pallas hash-agg
#: table is capped at this many rows — ONE shared constant so the two
#: eligibility checks cannot drift (see aggregate.seg_dims_for)
DIRECT_SEG_SMALL_LIMIT = 1 << 16

#: hash-agg bucket ways (bounded collision resolution, the probe-window twin)
AGG_WAYS = 8
#: input rows per grid block
AGG_BLOCK = 1024
#: compiled-mode table clamp: the key/count/accumulator tables are all
#: VMEM-resident across grid steps — 2^14 rows keeps a many-aggregate
#: table set under ~2 MB (see PROBE_MAX_BUILD_COMPILED's rationale)
AGG_TABLE_ROWS_COMPILED = 1 << 14

#: fused gather: total source bytes the kernel may keep resident
#: (interpret mode; the compiled clamp keeps the residency under VMEM)
GATHER_MAX_BYTES = 1 << 25
GATHER_MAX_BYTES_COMPILED = 1 << 22
GATHER_BLOCK = 1024
#: fusing fewer lanes than this is not worth a kernel launch
GATHER_MIN_COLS = 2


def mode() -> str:
    """Normalized ``IGLOO_TPU_PALLAS``: auto | 0 | 1 | interpret."""
    raw = os.environ.get("IGLOO_TPU_PALLAS", "auto").strip().lower()
    return raw if raw in ("0", "1", "interpret") else "auto"


def _backend() -> str:
    import jax
    return jax.default_backend()


def kernel_state() -> tuple:
    """(enabled, interpret) for the current mode + backend + x64 config.
    The kernels work on int64 hash/key lanes, so a 32-bit-only process
    never enables them."""
    m = mode()
    if m == "0":
        return False, False
    import jax
    if not jax.config.jax_enable_x64:
        return False, False
    if m == "interpret":
        return True, True
    if m == "1":
        return True, _backend() != "tpu"
    return (_backend() == "tpu"), False


def enabled() -> bool:
    return kernel_state()[0]


def cache_token() -> tuple:
    """Rides every jit cache key (Executor._jitted, the fused program key)
    so flipping IGLOO_TPU_PALLAS mid-process can never serve a program
    traced under the other mode."""
    return ("pallas",) + kernel_state()


def _fallback(kernel: str, reason: str) -> None:
    tracing.counter(f"pallas.fallback.{reason}")
    return None


# --- per-kernel planners (host-side; results are hashable cache-key parts) -

def plan_probe(build_cap: int, probe_cap: int,
               banned: bool = False) -> Optional[tuple]:
    """Plan the hash-probe kernel for a sorted-probe join, or None for the
    sort path. `build_cap`/`probe_cap` are canonical lane capacities."""
    on, interp = kernel_state()
    if not on:
        return None
    if banned:
        return _fallback("probe", "banned")
    if build_cap > (PROBE_MAX_BUILD if interp else PROBE_MAX_BUILD_COMPILED):
        return _fallback("probe", "too_big")
    nbuckets = min(max(canonical_capacity(build_cap) >> PROBE_BUCKET_SHIFT, 8),
                   PROBE_MAX_BUCKETS)
    block = pow2_block(probe_cap, PROBE_BLOCK)
    tracing.counter("pallas.probe")
    return ("probe", nbuckets, PROBE_WINDOW, block, interp)


def plan_segagg(pack_spec, n_keys: int, input_cap: int,
                banned: bool = False) -> Optional[tuple]:
    """Plan the one-pass hash aggregation for a sort-tier GROUP BY, or None.
    Requires a pack_spec covering EVERY key: the packed lane is then an
    exact (injective) group id, so table-key equality is group equality
    with no verify pass. All AggFunc members are supported."""
    on, interp = kernel_state()
    if not on:
        return None
    if banned:
        return _fallback("segagg", "banned")
    if pack_spec is None or len(pack_spec[1]) != n_keys:
        return _fallback("segagg", "unpackable")
    # 8x headroom over the input capacity keeps the per-bucket occupancy
    # low enough that `ways` slots rarely exhaust (overflow falls back)
    table = min(canonical_capacity(input_cap) * AGG_WAYS,
                DIRECT_SEG_SMALL_LIMIT if interp
                else AGG_TABLE_ROWS_COMPILED)
    nbuckets = max(table // AGG_WAYS, 8)
    block = pow2_block(input_cap, AGG_BLOCK)
    tracing.counter("pallas.segagg")
    return ("segagg", nbuckets, AGG_WAYS, block, interp)


def segagg_table_rows(plan: tuple) -> int:
    """Output capacity of a planned hash aggregation (a family member)."""
    return plan[1] * plan[2]


def _plan_gather(arrays: list, idx) -> Optional[tuple]:
    """Trace-time static decision for a batch gather; silent fallback (no
    counters for ineligibility — gathers are everywhere and most are too
    small or too wide to fuse)."""
    on, interp = kernel_state()
    if not on or len(arrays) < GATHER_MIN_COLS:
        return None
    if idx.ndim != 1 or any(a.ndim != 1 for a in arrays):
        return None
    m = arrays[0].shape[0]
    if any(a.shape[0] != m for a in arrays):
        return None
    n = idx.shape[0]
    block = pow2_block(n, GATHER_BLOCK)
    if n % block:
        return None
    budget = GATHER_MAX_BYTES if interp else GATHER_MAX_BYTES_COMPILED
    if sum(a.size * a.dtype.itemsize for a in arrays) > budget:
        return None
    tracing.counter("pallas.gather")
    return ("gather", block, interp)


# --- kernel wrappers (jit-traceable; the only pallas_kernels call sites) ---

def probe_bounds(plan: tuple, sorted_hash, probe_hash):
    """(lower, upper, overflow) — ``join._probe_bounds``'s contract over the
    ascending-sorted build hash multiset, plus the deferred overflow flag."""
    from igloo_tpu.exec import pallas_kernels
    _, nbuckets, window, block, interp = plan
    return pallas_kernels.hash_probe_bounds(sorted_hash, probe_hash,
                                            nbuckets, window, block, interp)


def segagg(plan: tuple, packed, live, ops: tuple, op_inputs: list):
    """(key_table, live_counts, per-op tables, overflow) — see
    ``pallas_kernels.hash_segagg``."""
    from igloo_tpu.exec import pallas_kernels
    _, nbuckets, ways, block, interp = plan
    return pallas_kernels.hash_segagg(packed, live, ops, op_inputs,
                                      nbuckets, ways, block, interp)


def gather_columns(arrays: list, idx) -> list:
    """Gather every lane in `arrays` by `idx`: the fused Pallas kernel when
    the mode and shapes allow, one ``jnp.take`` per lane otherwise."""
    plan = _plan_gather(arrays, idx)
    if plan is None:
        import jax.numpy as jnp
        return [jnp.take(a, idx) for a in arrays]
    from igloo_tpu.exec import pallas_kernels
    _, block, interp = plan
    return pallas_kernels.fused_gather(list(arrays), idx, block, interp)
