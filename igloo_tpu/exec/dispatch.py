"""Pallas kernel dispatch: the ONE gateway to ``exec/pallas_kernels.py``.

Every adoption site (join probe, sort-tier aggregation, batch gathers)
consults this module at PLAN/TRACE time — a host-side static decision that
callers fold into their jit cache keys (``cache_token()`` rides every
``Executor._jitted`` key and the fused program key, per-kernel plans ride
the per-op fingerprints) — and routes through the ``probe_bounds`` /
``segagg`` / ``gather_columns`` wrappers below, which are the only legal
callers of ``pallas_kernels`` (igloo-lint ``pallas-dispatch`` rule: the
flag and the fallback ladder must not be bypassable).

Knob: ``IGLOO_TPU_PALLAS``
  - ``auto`` (default)  kernels on TPU backends only, compiled;
  - ``0``               kernels off everywhere — reproduces the sort-path
                        plans and results bit-identically;
  - ``1``               kernels on; on non-TPU backends this implies the
                        Pallas interpreter (a compiled Pallas call needs
                        Mosaic/TPU);
  - ``interpret``       kernels on through the Pallas interpreter on any
                        backend — the CPU equivalence mode tier-1 uses.

Fallback ladder (each rung attributable): mode off / non-TPU auto -> sort
path silently; eligibility miss or an earlier failure's negative cache ->
sort path + ``pallas.fallback.<reason>``; COMPILE failure (a program the
backend cannot lower) -> caught at the executor's call sites, negative
cache + sort-path re-run (``pallas.compile_fallback``); runtime overflow
(probe window / agg table) -> deferred flag -> sort-path re-run +
negative cache (``pallas.probe_overflow`` / ``pallas.agg_overflow``).

Block shapes and table sizes derive from the canonical capacity families
(exec/capacity.py): lane capacities are family members (powers of two), so
``pow2_block`` blocks always divide them and kernel programs are keyed by
the same small shape family as the rest of the engine.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from igloo_tpu.exec.capacity import canonical_capacity, pow2_block
from igloo_tpu.utils import tracing

#: empty-slot sentinel in the hash-agg key table (canonical definition —
#: the kernels module imports it from here); packed key lanes are
#: mixed-radix digit strings and therefore always >= 0
EMPTY_KEY = np.int64(-1)

# --- kernel-eligibility bounds --------------------------------------------

#: per-probe-row bucket scan window (bounded ragged emission); a build-side
#: duplicate-key run longer than this overflows to the sort path
PROBE_WINDOW = 16
#: probe rows per grid block
PROBE_BLOCK = 1024
#: expected bucket occupancy target: buckets = build_capacity >> this
PROBE_BUCKET_SHIFT = 3
#: widest build side the probe kernel accepts in INTERPRET mode (the sorted
#: hash lane must be kernel-resident); matches the speculative-join budget
PROBE_MAX_BUILD = 1 << 22
#: compiled-mode clamp: the resident int64 hash lane must fit VMEM
#: (~16 MB/core) beside the bucket-starts lane and the probe blocks —
#: 2^20 lanes = 8 MB. A compile failure IS caught (the executor's
#: compile-failure rung), but it costs a wasted compile and permanently
#: bans the op, so the compiled bounds stay conservative.
PROBE_MAX_BUILD_COMPILED = 1 << 20
#: bucket-count clamp (the starts lane is a kernel input)
PROBE_MAX_BUCKETS = 1 << 19

#: the direct-scatter aggregate's "small segment space" bound: at or under
#: this many segments exec/aggregate.py scatters unconditionally; above it
#: the scatter path needs a tight aggregate budget and the Pallas hash-agg
#: table is capped at this many rows — ONE shared constant so the two
#: eligibility checks cannot drift (see aggregate.seg_dims_for)
DIRECT_SEG_SMALL_LIMIT = 1 << 16

#: hash-agg bucket ways (bounded collision resolution, the probe-window twin)
AGG_WAYS = 8
#: input rows per grid block
AGG_BLOCK = 1024
#: compiled-mode table clamp: the key/count/accumulator tables are all
#: VMEM-resident across grid steps — 2^14 rows keeps a many-aggregate
#: table set under ~2 MB (see PROBE_MAX_BUILD_COMPILED's rationale)
AGG_TABLE_ROWS_COMPILED = 1 << 14

#: fused gather: total source bytes the kernel may keep resident
#: (interpret mode; the compiled clamp keeps the residency under VMEM)
GATHER_MAX_BYTES = 1 << 25
GATHER_MAX_BYTES_COMPILED = 1 << 22
GATHER_BLOCK = 1024
#: fusing fewer lanes than this is not worth a kernel launch
GATHER_MIN_COLS = 2

#: match materialization: per-probe-row output window (the probe window's
#: twin — a row's match count is bounded by its probe run, so the same
#: default never overflows when the probe kernel didn't)
MATCH_WINDOW = 16
MATCH_BLOCK = 1024
#: the owner table is match-capacity-resident (int32): interpret / compiled
#: VMEM clamps, PROBE_MAX_BUILD's rationale
MATCH_MAX_CAP = 1 << 22
MATCH_MAX_CAP_COMPILED = 1 << 20

#: blocked partial top-k: per-block selection is k static min/mask rounds,
#: so k stays small (LIMIT + OFFSET; every TPC-H LIMIT is <= 100)
TOPK_MAX_K = 128
TOPK_BLOCK = 1024
TOPK_MAX_ROWS = 1 << 22
TOPK_MAX_ROWS_COMPILED = 1 << 20

#: exchange hash + partition scatter: padded row clamp (lanes are padded to
#: the canonical capacity family so kernel programs stay family-keyed),
#: bucket histogram residency, and the key-column fan-in
SCATTER_BLOCK = 1024
SCATTER_MAX_ROWS = 1 << 22
SCATTER_MAX_ROWS_COMPILED = 1 << 20
SCATTER_MAX_BUCKETS = 1 << 16
SCATTER_MAX_COLS = 8


def mode() -> str:
    """Normalized ``IGLOO_TPU_PALLAS``: auto | 0 | 1 | interpret."""
    raw = os.environ.get("IGLOO_TPU_PALLAS", "auto").strip().lower()
    return raw if raw in ("0", "1", "interpret") else "auto"


def _backend() -> str:
    import jax
    return jax.default_backend()


def kernel_state() -> tuple:
    """(enabled, interpret) for the current mode + backend + x64 config.
    The kernels work on int64 hash/key lanes, so a 32-bit-only process
    never enables them."""
    m = mode()
    if m == "0":
        return False, False
    import jax
    if not jax.config.jax_enable_x64:
        return False, False
    if m == "interpret":
        return True, True
    if m == "1":
        return True, _backend() != "tpu"
    return (_backend() == "tpu"), False


def enabled() -> bool:
    return kernel_state()[0]


def cache_token() -> tuple:
    """Rides every jit cache key (Executor._jitted, the fused program key)
    so flipping IGLOO_TPU_PALLAS mid-process can never serve a program
    traced under the other mode. The autotune table version rides along for
    the same reason: adopting new tuned shapes (locally or via cluster
    replication) must re-trace every kernel-bearing program, never serve a
    trace planned under the old shapes."""
    from igloo_tpu.exec import autotune
    return ("pallas",) + kernel_state() + (autotune.table_version(),)


def _tuned(kernel: str, cap: int) -> dict:
    """Autotuned shape overrides for (kernel, canonical capacity) — {} when
    autotuning is off or no winner is persisted (module defaults apply)."""
    from igloo_tpu.exec import autotune
    return autotune.shapes(kernel, cap)


def _fallback(kernel: str, reason: str) -> None:
    tracing.counter(f"pallas.fallback.{reason}")
    return None


# --- per-kernel planners (host-side; results are hashable cache-key parts) -

def plan_probe(build_cap: int, probe_cap: int,
               banned: bool = False) -> Optional[tuple]:
    """Plan the hash-probe kernel for a sorted-probe join, or None for the
    sort path. `build_cap`/`probe_cap` are canonical lane capacities."""
    on, interp = kernel_state()
    if not on:
        return None
    if banned:
        return _fallback("probe", "banned")
    if build_cap > (PROBE_MAX_BUILD if interp else PROBE_MAX_BUILD_COMPILED):
        return _fallback("probe", "too_big")
    tuned = _tuned("probe", canonical_capacity(build_cap))
    shift = int(tuned.get("bucket_shift", PROBE_BUCKET_SHIFT))
    nbuckets = min(max(canonical_capacity(build_cap) >> shift, 8),
                   PROBE_MAX_BUCKETS)
    block = pow2_block(probe_cap, int(tuned.get("block", PROBE_BLOCK)))
    tracing.counter("pallas.probe")
    return ("probe", nbuckets, int(tuned.get("window", PROBE_WINDOW)),
            block, interp)


def plan_segagg(pack_spec, n_keys: int, input_cap: int,
                banned: bool = False) -> Optional[tuple]:
    """Plan the one-pass hash aggregation for a sort-tier GROUP BY, or None.
    Requires a pack_spec covering EVERY key: the packed lane is then an
    exact (injective) group id, so table-key equality is group equality
    with no verify pass. All AggFunc members are supported."""
    on, interp = kernel_state()
    if not on:
        return None
    if banned:
        return _fallback("segagg", "banned")
    if pack_spec is None or len(pack_spec[1]) != n_keys:
        return _fallback("segagg", "unpackable")
    # 8x headroom over the input capacity keeps the per-bucket occupancy
    # low enough that `ways` slots rarely exhaust (overflow falls back)
    tuned = _tuned("segagg", canonical_capacity(input_cap))
    ways = int(tuned.get("ways", AGG_WAYS))
    table = min(canonical_capacity(input_cap) * ways,
                DIRECT_SEG_SMALL_LIMIT if interp
                else AGG_TABLE_ROWS_COMPILED)
    nbuckets = max(table // ways, 8)
    block = pow2_block(input_cap, int(tuned.get("block", AGG_BLOCK)))
    tracing.counter("pallas.segagg")
    return ("segagg", nbuckets, ways, block, interp)


def segagg_table_rows(plan: tuple) -> int:
    """Output capacity of a planned hash aggregation (a family member)."""
    return plan[1] * plan[2]


def _plan_gather(arrays: list, idx) -> Optional[tuple]:
    """Trace-time static decision for a batch gather; silent fallback (no
    counters for ineligibility — gathers are everywhere and most are too
    small or too wide to fuse)."""
    on, interp = kernel_state()
    if not on or len(arrays) < GATHER_MIN_COLS:
        return None
    if idx.ndim != 1 or any(a.ndim != 1 for a in arrays):
        return None
    m = arrays[0].shape[0]
    if any(a.shape[0] != m for a in arrays):
        return None
    n = idx.shape[0]
    block = pow2_block(n, GATHER_BLOCK)
    if n % block:
        return None
    budget = GATHER_MAX_BYTES if interp else GATHER_MAX_BYTES_COMPILED
    if sum(a.size * a.dtype.itemsize for a in arrays) > budget:
        return None
    tracing.counter("pallas.gather")
    return ("gather", block, interp)


# --- kernel wrappers (jit-traceable; the only pallas_kernels call sites) ---

def probe_bounds(plan: tuple, sorted_hash, probe_hash):
    """(lower, upper, overflow) — ``join._probe_bounds``'s contract over the
    ascending-sorted build hash multiset, plus the deferred overflow flag."""
    from igloo_tpu.exec import pallas_kernels
    _, nbuckets, window, block, interp = plan
    return pallas_kernels.hash_probe_bounds(sorted_hash, probe_hash,
                                            nbuckets, window, block, interp)


def segagg(plan: tuple, packed, live, ops: tuple, op_inputs: list):
    """(key_table, live_counts, per-op tables, overflow) — see
    ``pallas_kernels.hash_segagg``."""
    from igloo_tpu.exec import pallas_kernels
    _, nbuckets, ways, block, interp = plan
    return pallas_kernels.hash_segagg(packed, live, ops, op_inputs,
                                      nbuckets, ways, block, interp)


def gather_columns(arrays: list, idx) -> list:
    """Gather every lane in `arrays` by `idx`: the fused Pallas kernel when
    the mode and shapes allow, one ``jnp.take`` per lane otherwise."""
    plan = _plan_gather(arrays, idx)
    if plan is None:
        import jax.numpy as jnp
        return [jnp.take(a, idx) for a in arrays]
    from igloo_tpu.exec import pallas_kernels
    _, block, interp = plan
    return pallas_kernels.fused_gather(list(arrays), idx, block, interp)


def plan_match(probe_cap: int, match_cap: int,
               banned: bool = False) -> Optional[tuple]:
    """Plan match materialization for ``join.expand_phase``: route "kernel"
    (one blocked Pallas pass, bounded window, deferred overflow) when the
    kernels are on and the shapes fit; route "search" (an exact searchsorted
    inversion of the prefix lane — the algorithmic fast path the non-Pallas
    tier keeps) otherwise. A ban (earlier overflow/compile failure) demotes
    the kernel route to "search", never all the way to the scan."""
    on, interp = kernel_state()
    if on and not banned:
        if match_cap <= (MATCH_MAX_CAP if interp else MATCH_MAX_CAP_COMPILED):
            tuned = _tuned("match", canonical_capacity(match_cap))
            block = pow2_block(probe_cap,
                               int(tuned.get("block", MATCH_BLOCK)))
            tracing.counter("pallas.match")
            return ("match", "kernel",
                    int(tuned.get("window", MATCH_WINDOW)), block, interp)
        _fallback("match", "too_big")
    elif on and banned:
        _fallback("match", "banned")
    if _backend() == "tpu" and not interp:
        # on real TPU hardware the scatter+cummax scan beats a searchsorted
        # over the match lane (a ~23-pass gather loop — see expand_phase)
        return None
    tracing.counter("join.match_search")
    return ("match", "search")


def plan_topk(cap: int, k: int, full_pack: bool,
              banned: bool = False) -> Optional[tuple]:
    """Plan a partial top-k for LIMIT-over-ORDER-BY, or None for the full
    sort path. Mode-independent: route "alg" (``lax.top_k`` over the packed
    sort key — ties are lowest-index-first, the stable argsort's first-k
    order) is pure XLA and wins on every tier; route "pallas" is the blocked
    kernel. `k` is LIMIT + OFFSET; `full_pack` means the prefix packing
    covers EVERY sort key (a single totally-ordered lane — partial packs
    still need the lexicographic tiebreak sort)."""
    if k <= 0 or cap <= 0:
        return None
    if not full_pack:
        return _fallback("topk", "unpackable")
    if 2 * k > cap:
        # LIMIT covers most of the batch: a partial top-k (and the packed
        # prefix it rides on) buys nothing — take the direct sort path
        return _fallback("topk", "large_limit")
    on, interp = kernel_state()
    if on and not banned and k <= TOPK_MAX_K and \
            cap <= (TOPK_MAX_ROWS if interp else TOPK_MAX_ROWS_COMPILED):
        tuned = _tuned("topk", canonical_capacity(cap))
        block = pow2_block(cap, int(tuned.get("block", TOPK_BLOCK)))
        if k <= block:
            tracing.counter("pallas.topk")
            return ("topk", "pallas", k, block, interp)
    tracing.counter("topk.alg")
    return ("topk", "alg", k)


def plan_scatter(nrows: int, ncols: int, nbuckets: int,
                 banned: bool = False) -> Optional[tuple]:
    """Plan the fused exchange hash + partition scatter, or None for the
    numpy path. `nrows` is the raw table length (lanes are padded to its
    canonical capacity so programs stay family-keyed), `ncols` the key
    fan-in, `nbuckets` the exchange bucket count."""
    on, interp = kernel_state()
    if not on or ncols == 0 or nrows == 0:
        return None
    if banned:
        return _fallback("scatter", "banned")
    npad = canonical_capacity(nrows)
    if ncols > SCATTER_MAX_COLS or nbuckets > SCATTER_MAX_BUCKETS or \
            npad > (SCATTER_MAX_ROWS if interp else SCATTER_MAX_ROWS_COMPILED):
        return _fallback("scatter", "too_big")
    tuned = _tuned("scatter", npad)
    block = pow2_block(npad, int(tuned.get("block", SCATTER_BLOCK)))
    tracing.counter("pallas.scatter")
    return ("scatter", npad, nbuckets, block, interp)


def match_table(plan: tuple, prefix, counts, match_cap: int):
    """(owner, overflow) — the slot-ownership table ``join.expand_phase``
    derives by owner-scatter + associative scan, via the Pallas match
    kernel (route "kernel" plans only)."""
    from igloo_tpu.exec import pallas_kernels
    _, _, window, block, interp = plan
    return pallas_kernels.match_owner_table(prefix, counts, match_cap,
                                            window, block, interp)


def topk_perm(plan: tuple, sort_key):
    """Positions of the k smallest packed sort keys, in the full stable
    ascending order's first-k sequence (ties lowest-position-first)."""
    import jax
    import jax.numpy as jnp
    if plan[1] == "alg":
        k = plan[2]
        return jax.lax.top_k(-sort_key, k)[1].astype(jnp.int32)
    from igloo_tpu.exec import pallas_kernels
    _, _, k, block, interp = plan
    ckeys, cpos = pallas_kernels.blocked_topk(sort_key, k, block, interp)
    # candidates are block-major with position-ascending ties inside AND
    # across blocks, so a stable argsort reproduces the global stable order
    order = jnp.argsort(ckeys, stable=True)
    return jnp.take(cpos, order[:k])


def exchange_scatter(plan: tuple, val_lanes: list):
    """(bucket_ids, order, counts) for an exchange partition — numpy arrays
    bit-identical to ``exchange.bucket_ids`` + stable argsort + bincount.
    `val_lanes` are the host-side canonical pre-mix uint64 lanes
    (``exchange._column_vals``); padding to the canonical capacity and the
    final stable sort of the bucket lane happen device-side."""
    import jax.numpy as jnp
    from igloo_tpu.exec import pallas_kernels
    _, npad, nbuckets, block, interp = plan
    n = int(val_lanes[0].shape[0])
    pad = npad - n
    lanes = [jnp.asarray(np.pad(v, (0, pad))) for v in val_lanes]
    live = jnp.arange(npad) < n
    pid_full, counts = pallas_kernels.hash_scatter(lanes, live, nbuckets,
                                                   block, interp)
    pid = pid_full[:n]
    order = jnp.argsort(pid, stable=True)
    return (np.asarray(pid).astype(np.int64), np.asarray(order),
            np.asarray(counts))
