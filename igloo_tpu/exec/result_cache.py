"""Query-result cache: plan fingerprint -> host Arrow table.

This is the reference cache's actual shape — `Cache` maps query strings to
RecordBatch vectors (crates/cache/src/lib.rs:20-56) — layered ABOVE the HBM
scan cache (exec/cache.py): a repeated query skips parsing nothing (the plan
fingerprint needs the bind) but skips ALL device execution. Entries are
validated against the snapshot tokens of every scanned provider — including
scans inside scalar subqueries — so source changes invalidate exactly like
the scan cache; byte-budget LRU bounds memory (the reference's
CacheConfig.capacity is declared and never enforced, G7).

Keys are the serialized bound plan (cluster/serde.py), not the SQL text: two
spellings of the same plan share an entry, and unserializable plans simply
skip the cache.
"""
from __future__ import annotations

import hashlib
import json
from typing import Optional

import pyarrow as pa

from igloo_tpu.exec.cache import SnapshotLRU


def _collect_scans(plan, tables: list, snaps: list) -> None:
    """Every Scan in the plan tree AND in scalar-subquery plans embedded in
    its expressions (walk_plan never descends into expressions, but a
    subquery's source changing must invalidate the cached result too)."""
    from igloo_tpu.exec.cache import provider_snapshot, scan_table_key
    from igloo_tpu.plan import expr as E
    from igloo_tpu.plan import logical as L
    for node in L.walk_plan(plan):
        if isinstance(node, L.Scan) and node.provider is not None:
            tables.append(scan_table_key(node.table))
            snaps.append(provider_snapshot(node.provider))
        for e in _node_exprs(node):
            if e is None:
                continue
            for n in E.walk(e):
                if isinstance(n, E.ScalarSubquery) and \
                        isinstance(n.query, L.LogicalPlan):
                    _collect_scans(n.query, tables, snaps)


def _node_exprs(node) -> list:
    from igloo_tpu.plan import logical as L
    if isinstance(node, L.Scan):
        return list(node.pushed_filters)
    if isinstance(node, L.Filter):
        return [node.predicate]
    if isinstance(node, L.Project):
        return list(node.exprs)
    if isinstance(node, L.Aggregate):
        return list(node.group_exprs) + list(node.aggs)
    if isinstance(node, L.Join):
        return list(node.left_keys) + list(node.right_keys) + [node.residual]
    if isinstance(node, L.Sort):
        return list(node.keys)
    return []


def plan_cache_key(plan) -> Optional[tuple]:
    """(digest, scanned tables, snapshot tokens) for a bound plan, or None if
    the plan can't be fingerprinted (unserializable node)."""
    from igloo_tpu.cluster import serde
    try:
        blob = json.dumps(serde.plan_to_json(plan), sort_keys=True,
                          default=str)
    except Exception:
        return None
    tables: list = []
    snaps: list = []
    _collect_scans(plan, tables, snaps)
    digest = hashlib.sha256(blob.encode()).hexdigest()
    return digest, tuple(tables), tuple(snaps)


class ResultCache(SnapshotLRU):
    """Host-side result cache over the shared snapshot-validated LRU.

    Bounded two ways: a byte budget AND an entry-count capacity (the
    reference's `CacheConfig.capacity`, enforced here — gap G7 closed).
    Dashboards repeat a few hundred distinct queries; past that, extra
    entries are churn that slows every snapshot sweep. Entry-capacity
    evictions bump `result_cache.evicted` (byte-budget ones
    `result_cache.evict`)."""

    counter_prefix = "result_cache"

    DEFAULT_CAPACITY = 512

    def __init__(self, budget_bytes: int = 256 << 20,
                 capacity: Optional[int] = DEFAULT_CAPACITY):
        super().__init__(budget_bytes, capacity=capacity)

    def get(self, key: tuple) -> Optional[pa.Table]:  # type: ignore[override]
        digest, _tables, snaps = key
        return super().get(digest, snaps)

    def put(self, key: tuple, table: pa.Table) -> None:  # type: ignore[override]
        digest, tables, snaps = key
        super().put(digest, table, snaps, table.nbytes,
                    tables=frozenset(tables))

    def _match_table(self, key, entry, table_key: str) -> bool:
        return table_key in entry.tables
