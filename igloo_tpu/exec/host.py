"""Host (numpy) execution tier for small queries.

On a tunneled TPU every dispatch+readback costs ~0.1-0.3 s, so a query whose
sources total a few MB can never win on the device — the round-4 bench lost
q2/q11/q16 to single-threaded pandas purely on that floor. XLA:CPU is not the
answer either: the engine's device kernels are static-shape/sort-based designs
(the right trade on a TPU), and replaying them on a small host loses ~3-10x to
numpy's dynamic-shape primitives (measured: 1-core XLA:CPU argsort of 1M int64
= 0.34 s vs numpy 0.13 s, and the padded-lane kernels multiply that).

So the host tier is a third executor with HOST-shaped algorithms: compact
arrays, dynamic shapes, np.unique/searchsorted joins and bincount/reduceat
aggregation — the same logical operators, re-designed for the memory hierarchy
they run on, exactly like the device kernels are designed for theirs. It
covers the plan/expression surface small analytical queries use; anything else
raises HostUnsupported and the engine falls back to the device path (the
routing threshold lives in QueryEngine.host_route_bytes).

The reference has no analog (its engine IS a host engine); parity-wise this
replaces nothing and exists because the accelerator is remote.

Semantics mirror the device expression compiler (exec/expr_compile.py):
3-valued logic with separate null lanes, x/0 -> NULL, SQL truncating integer
division, date lanes in days / timestamps in microseconds.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, replace
from typing import Callable, Optional

import numpy as np
import pyarrow as pa

from igloo_tpu import types as T
from igloo_tpu.errors import ExecError, PlanError
from igloo_tpu.exec.batch import DictInfo, host_decode_column
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.sql.ast import JoinType
from igloo_tpu.utils import stats, tracing


class HostUnsupported(Exception):
    """Plan/expression feature outside the host tier; caller falls back."""


@dataclass
class HCol:
    dtype: T.DataType
    values: np.ndarray                 # lane dtype; STRING = int32 codes
    nulls: Optional[np.ndarray]        # bool, True = null; None = no nulls
    dict: Optional[DictInfo] = None    # STRING columns


@dataclass
class HBatch:
    schema: T.Schema
    cols: list
    n: int

    def col(self, i: int) -> HCol:
        return self.cols[i]

    def take(self, idx: np.ndarray) -> "HBatch":
        return HBatch(self.schema,
                      [HCol(c.dtype, c.values[idx],
                            c.nulls[idx] if c.nulls is not None else None,
                            c.dict) for c in self.cols], len(idx))

    def mask(self, m: np.ndarray) -> "HBatch":
        return self.take(np.nonzero(m)[0])


def _or_nulls(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def _valid(n, nulls):
    return np.ones(n, dtype=bool) if nulls is None else ~nulls


def _materialize_str(c: HCol) -> np.ndarray:
    """codes+dict -> numpy unicode array (null lanes hold '')."""
    if c.dict is None or len(c.dict) == 0:
        return np.full(len(c.values), "", dtype=object).astype(str)
    return c.dict.values.astype(str)[np.clip(c.values, 0, len(c.dict) - 1)]


_LIKE_CACHE: dict = {}


def _like_regex(pattern: str, case_insensitive: bool):
    key = (pattern, case_insensitive)
    rx = _LIKE_CACHE.get(key)
    if rx is None:
        parts = []
        for ch in pattern:
            if ch == "%":
                parts.append(".*")
            elif ch == "_":
                parts.append(".")
            else:
                parts.append(re.escape(ch))
        rx = re.compile("^" + "".join(parts) + "$",
                        re.IGNORECASE if case_insensitive else 0)
        _LIKE_CACHE[key] = rx
    return rx


def _vector_match(sv: np.ndarray, pattern: str, ci: bool) -> np.ndarray:
    """Vectorized LIKE over string values (pandas' C matcher; a python re
    loop over a TPC-H comment column is ~10x slower)."""
    import pandas as pd
    rx = _like_regex(pattern, ci)
    return pd.Series(sv).str.match(rx).to_numpy(dtype=bool)


def _like_lut(d: DictInfo, pattern: str, ci: bool) -> np.ndarray:
    """Per-dictionary-entry LIKE results, memoized on the DictInfo object:
    with the host scan cache holding dictionaries across queries, a repeated
    filter costs one gather instead of a match over every entry."""
    cache = getattr(d, "_like_luts", None)
    if cache is None:
        cache = {}
        object.__setattr__(d, "_like_luts", cache)
    key = (pattern, ci)
    lut = cache.get(key)
    if lut is None:
        lut = _vector_match(d.values.astype(str), pattern, ci)
        cache[key] = lut
    return lut


def _civil_from_days(days: np.ndarray):
    d64 = days.astype("datetime64[D]")
    y = d64.astype("datetime64[Y]").astype(np.int64) + 1970
    m = d64.astype("datetime64[M]").astype(np.int64) % 12 + 1
    day = (d64 - d64.astype("datetime64[M]")).astype(np.int64) + 1
    return y.astype(np.int32), m.astype(np.int32), day.astype(np.int32)


class HostExecutor:
    """Executes a bound+optimized LogicalPlan with numpy. One instance per
    query (subquery resolution recurses through `self`)."""

    # cross joins beyond this many output rows are not a "small query"
    _CROSS_LIMIT = 4_000_000

    def __init__(self, catalog=None, scan_cache=None):
        self.catalog = catalog
        # host-RAM decoded-column cache (SnapshotLRU), engine-owned: decode +
        # dictionary-encode of a column happens once, not once per query —
        # the pandas baseline gets its DataFrames pre-loaded, so must we
        self._scan_cache = scan_cache
        # intra-query structural memo: a scalar subquery usually shares its
        # join/aggregate subtree with the outer query (TPC-H q11/q15/q22);
        # executing the identical subtree once halves those queries. HBatches
        # are immutable by convention, so sharing is safe.
        self._memo: dict = {}

    # ---- public ----------------------------------------------------------

    def execute_to_arrow(self, plan: L.LogicalPlan) -> pa.Table:
        tracing.counter("host.execute")
        return to_arrow(self._exec(plan))

    # ---- dispatch --------------------------------------------------------

    def _exec(self, plan: L.LogicalPlan) -> HBatch:
        m = getattr(self, "_exec_" + type(plan).__name__.lower(), None)
        if m is None:
            raise HostUnsupported(type(plan).__name__)
        key = None
        if isinstance(plan, (L.Join, L.Aggregate)):
            key = self._plan_fp(plan)
            hit = self._memo.get(key) if key is not None else None
            if hit is not None:
                served = _serve_by_name(hit, plan.schema)
                if served is not None:
                    tracing.counter("host.memo_hit")
                    with stats.plan_op(plan):
                        stats.set_rows(served.n)
                        stats.annotate(memo="hit")
                    return served
        with stats.plan_op(plan):
            out = m(plan)
            # numpy row counts are host values: actual rows are FREE on this
            # tier, recorded at every collection level
            stats.set_rows(out.n)
        if not isinstance(plan, L.Scan):
            # feed the adaptive planner loop (docs/adaptive.md): the count is
            # already in hand, so the observation is one tuple append. The
            # fingerprint recursion is the real cost on this sub-0.1s tier,
            # so it only runs when the loop is on and no memo key exists
            from igloo_tpu.exec.hints import adaptive_enabled
            if adaptive_enabled():
                fp = key if key is not None else self._plan_fp(plan)
                if fp is not None:
                    stats.observe_card(fp, out.n)
        if out.schema is not plan.schema and out.schema != plan.schema:
            out = HBatch(plan.schema, out.cols, out.n)
        if key is not None and (key not in self._memo or
                                len(out.schema) >
                                len(self._memo[key].schema)):
            self._memo[key] = out
        return out

    @classmethod
    def _plan_fp(cls, plan: L.LogicalPlan):
        """Projection-INSENSITIVE structural fingerprint (exec/hints.plan_fp,
        shared with every AdaptiveStats producer/consumer): a scalar
        subquery's join subtree then hits the outer query's memo entry even
        though pruning gave it a narrower scan, and the hit is served by
        name (_serve_by_name) — TPC-H q2/q11/q15/q22 halve."""
        from igloo_tpu.exec.hints import plan_fp
        return plan_fp(plan)

    # ---- leaves ----------------------------------------------------------

    def _exec_scan(self, plan: L.Scan) -> HBatch:
        from igloo_tpu.exec.executor import read_scan_table
        cache = self._scan_cache
        stable = getattr(plan.provider, "stable_row_order", False)
        if cache is None or not stable:
            table = read_scan_table(plan)
            if plan.projection is not None:
                table = table.select(plan.projection)
            cols = []
            for f in plan.schema:
                vals, nulls, dinfo, _b = host_decode_column(
                    table.column(f.name), f)
                cols.append(HCol(f.dtype, vals, nulls, dinfo))
            return HBatch(plan.schema, cols, table.num_rows)
        from igloo_tpu.exec.cache import provider_snapshot
        from igloo_tpu.exec.executor import expr_fingerprint
        snap = provider_snapshot(plan.provider)
        base = (plan.table, expr_fingerprint(plan.pushed_filters),
                plan.partition, "host")
        if not plan.schema.fields:  # zero-column scan: only the count matters
            table = read_scan_table(plan)
            return HBatch(plan.schema, [], table.num_rows)
        cached = {f.name: cache.get(base + (f.name,), snap)
                  for f in plan.schema}
        missing = [f for f in plan.schema if cached[f.name] is None]
        known_n = next((v[1] for v in cached.values() if v is not None),
                       None)
        if missing:
            proj = [f.name for f in missing]
            table = read_scan_table(plan, projection=proj).select(proj)
            if known_n is not None and table.num_rows != known_n:
                # source changed under an identity snapshot: never stitch
                # columns from different row sets
                cache.invalidate_table(plan.table)
                return self._exec_scan(plan)
            for f in missing:
                vals, nulls, dinfo, _b = host_decode_column(
                    table.column(f.name), f)
                col = HCol(f.dtype, vals, nulls, dinfo)
                nb = vals.nbytes + (nulls.nbytes if nulls is not None else 0)
                cache.put_entry(base + (f.name,), (col, table.num_rows),
                                snap, nb, plan.table)
                cached[f.name] = (col, table.num_rows)
        n = next(v[1] for v in cached.values())
        return HBatch(plan.schema,
                      [cached[f.name][0] for f in plan.schema], n)

    def _exec_values(self, plan: L.Values) -> HBatch:
        from igloo_tpu.exec.batch import from_arrow  # noqa: F401  (parity)
        n = len(plan.rows)
        cols = []
        for j, f in enumerate(plan.schema):
            vals = [r[j] for r in plan.rows]
            arr = pa.array(vals, type=_pa_for(f.dtype))
            v, nulls, dinfo, _ = host_decode_column(arr, f)
            cols.append(HCol(f.dtype, v, nulls, dinfo))
        return HBatch(plan.schema, cols, n)

    # ---- row-wise --------------------------------------------------------

    def _exec_filter(self, plan: L.Filter) -> HBatch:
        b = self._exec(plan.input)
        v, nulls = self._eval_bool(plan.predicate, b)
        keep = v & _valid(b.n, nulls)
        return b.mask(keep)

    def _exec_project(self, plan: L.Project) -> HBatch:
        b = self._exec(plan.input)
        cols = [self._eval_col(e, b, f.dtype)
                for e, f in zip(plan.exprs, plan.schema)]
        return HBatch(plan.schema, cols, b.n)

    def _exec_limit(self, plan: L.Limit) -> HBatch:
        b = self._exec(plan.input)
        lo = plan.offset
        hi = b.n if plan.limit is None else min(b.n, lo + plan.limit)
        return b.take(np.arange(lo, max(lo, hi)))

    # ---- sort ------------------------------------------------------------

    def _sort_order(self, b: HBatch, keys, ascending, nulls_first,
                    stable=True) -> np.ndarray:
        lex = []  # np.lexsort: LAST key is primary
        for e, asc, nf in reversed(list(zip(keys, ascending, nulls_first))):
            c = self._eval_col(e, b, e.dtype)
            if c.dtype.is_string:
                if c.dict is not None:
                    k = c.dict.ranks().astype(np.int64)[
                        np.clip(c.values, 0, max(len(c.dict) - 1, 0))] \
                        if len(c.dict or []) else np.zeros(b.n, np.int64)
                else:
                    sv = c.values.astype(str)
                    k = np.unique(sv, return_inverse=True)[1]
            elif c.dtype.id == T.TypeId.BOOL:
                k = c.values.astype(np.int64)
            else:
                k = c.values
            if not asc:
                if k.dtype.kind == "f":
                    k = -k
                else:
                    k = -(k.astype(np.int64))
            nullk = np.zeros(b.n, dtype=np.int8)
            if c.nulls is not None:
                nullk = np.where(c.nulls, -1 if nf else 1, 0).astype(np.int8)
            lex.append(k)
            lex.append(nullk)
        return np.lexsort(lex) if lex else np.arange(b.n)

    def _exec_sort(self, plan: L.Sort) -> HBatch:
        b = self._exec(plan.input)
        order = self._sort_order(b, plan.keys, plan.ascending,
                                 plan.nulls_first)
        return b.take(order)

    # ---- distinct --------------------------------------------------------

    def _group_codes(self, cols: list, n: int) -> tuple:
        """-> (inverse codes int64[n], n_groups). Null participates as its own
        value (SQL GROUP BY/DISTINCT treat nulls as equal)."""
        if not cols:
            return np.zeros(n, dtype=np.int64), 1 if n else 0
        invs, cards = [], []
        for c in cols:
            if c.dtype.is_string and c.dict is not None:
                base = c.values.astype(np.int64)
                card = max(len(c.dict), 1)
            else:
                vals = c.values
                nan = None
                if vals.dtype.kind == "f":
                    # canonicalize -0.0; NaN gets its OWN slot below (mapping
                    # it onto inf would merge two distinct SQL groups)
                    nan = np.isnan(vals)
                    vals = np.where(nan, 0.0, vals + 0.0)
                u, base = np.unique(vals, return_inverse=True)
                card = max(len(u), 1)
                if nan is not None and nan.any():
                    base = np.where(nan, card, base)
                    card += 1
            if c.nulls is not None:
                base = np.where(c.nulls, card, base)
                card += 1
            invs.append(base.astype(np.int64))
            cards.append(card)
        total_bits = sum(int(np.ceil(np.log2(max(cd, 2)))) for cd in cards)
        if total_bits < 62:
            mixed = invs[0]
            for iv, cd in zip(invs[1:], cards[1:]):
                mixed = mixed * cd + iv
        else:
            _, mixed = np.unique(np.stack(invs, axis=1), axis=0,
                                 return_inverse=True)
        _, first, inv = np.unique(mixed, return_index=True,
                                  return_inverse=True)
        return inv, len(first)

    def _exec_distinct(self, plan: L.Distinct) -> HBatch:
        b = self._exec(plan.input)
        inv, _k = self._group_codes(b.cols, b.n)
        # first occurrence of each group, in input order
        first = np.zeros(0, dtype=np.int64)
        if b.n:
            order = np.argsort(inv, kind="stable")
            boundaries = np.ones(b.n, dtype=bool)
            boundaries[1:] = inv[order][1:] != inv[order][:-1]
            first = np.sort(order[boundaries])
        return b.take(first)

    # ---- aggregate -------------------------------------------------------

    def _group_direct(self, gcols: list, n: int):
        """Sort-free grouping: when every key is a dense-int / dictionary /
        bool lane, group ids are direct offsets and the key VALUES decode
        from the slot id — no np.unique (a full sort) and no representative
        gather. Returns (inv, card, decode) or None for the generic path."""
        parts = []  # (card, decoder(slots)->HCol)
        inv = None
        prod = 1  # running COMBINED cardinality: the direct arrays (bincount
        # targets, per-aggregate outputs) are prod-sized, so the same dense
        # bound that limits each key's span must limit their product — two
        # ~4n-span keys would otherwise attempt ~16n^2-slot allocations
        for c in gcols:
            nulls = c.nulls if c.nulls is not None and c.nulls.any() else None
            if c.dtype.is_string and c.dict is not None:
                card = max(len(c.dict), 1)
                codes = c.values.astype(np.int64)

                def dec(slots, isnull, c=c):
                    return HCol(c.dtype, slots.astype(np.int32),
                                isnull, c.dict)
            elif c.dtype.id == T.TypeId.BOOL:
                card = 2
                codes = c.values.astype(np.int64)

                def dec(slots, isnull, c=c):
                    return HCol(c.dtype, slots.astype(bool), isnull)
            elif c.values.dtype.kind in "iu":
                if n == 0:
                    lo, hi = 0, 0
                else:
                    lo, hi = int(c.values.min()), int(c.values.max())
                span = hi - lo + 1
                if span > 4 * n + 1024:
                    return None  # sparse keys: direct slots would explode
                card = span
                codes = (c.values - lo).astype(np.int64)

                def dec(slots, isnull, c=c, lo=lo):
                    return HCol(c.dtype,
                                (slots + lo).astype(c.values.dtype), isnull)
            else:
                return None  # float keys: generic path
            if nulls is not None:
                codes = np.where(nulls, card, codes)
                card += 1
                base_dec = dec

                def dec(slots, isnull, base_dec=base_dec, card=card):
                    isn = slots == card - 1
                    col = base_dec(np.where(isn, 0, slots), None)
                    return replace(col, nulls=isn if isn.any() else None)
            prod *= card
            if prod > 4 * n + 1024:
                return None  # combined slot space would dwarf the input
            parts.append((card, dec))
            inv = codes if inv is None else inv * card + codes
        card = prod

        def decode(slots):
            cols, rest = [], slots
            for cd, dec in reversed(parts):
                cols.append((dec, rest % cd))
                rest = rest // cd
            return [dec(sl, None) for dec, sl in reversed(cols)]
        return inv, card, decode

    def _exec_aggregate(self, plan: L.Aggregate) -> HBatch:
        b = self._exec(plan.input)
        gcols = [self._eval_col(e, b, e.dtype) for e in plan.group_exprs]
        no_groups = not plan.group_exprs
        if no_groups:
            inv = np.zeros(b.n, dtype=np.int64)
            out_cols = []
            for agg, f in zip(plan.aggs, plan.schema.fields):
                out_cols.append(self._agg_one(agg, f.dtype, b, inv, 1))
            return HBatch(plan.schema, out_cols, 1)
        direct = self._group_direct(gcols, b.n) if b.n else None
        if direct is not None:
            inv, card, decode = direct
            occupied = np.bincount(inv, minlength=card) > 0
            slots = np.nonzero(occupied)[0]
            out_cols = decode(slots)
            for agg, f in zip(plan.aggs, plan.schema.fields[len(gcols):]):
                full = self._agg_one(agg, f.dtype, b, inv, card)
                out_cols.append(HCol(full.dtype, full.values[slots],
                                     full.nulls[slots]
                                     if full.nulls is not None else None,
                                     full.dict))
            return HBatch(plan.schema, out_cols, len(slots))
        inv, k = self._group_codes(gcols, b.n)
        # representative row per group (group order is unspecified by SQL)
        if b.n:
            order = np.argsort(inv, kind="stable")
            bnd = np.ones(b.n, dtype=bool)
            bnd[1:] = inv[order][1:] != inv[order][:-1]
            reps = order[bnd]
        else:
            reps = np.zeros(0, dtype=np.int64)
        out_cols = [HCol(c.dtype, c.values[reps],
                         c.nulls[reps] if c.nulls is not None else None,
                         c.dict) for c in gcols]
        nk = len(reps)
        for agg, f in zip(plan.aggs, plan.schema.fields[len(gcols):]):
            out_cols.append(self._agg_one(agg, f.dtype, b, inv, nk))
        return HBatch(plan.schema, out_cols, nk)

    def _agg_one(self, agg: E.Aggregate, out_dtype, b: HBatch,
                 inv: np.ndarray, k: int) -> HCol:
        AF = E.AggFunc
        if agg.func is AF.COUNT_STAR:
            cnt = np.bincount(inv, minlength=k).astype(np.int64)
            return HCol(out_dtype, cnt, None)
        c = self._eval_col(agg.arg, b, agg.arg.dtype)
        valid = _valid(b.n, c.nulls)
        vinv, n_valid = inv[valid], int(valid.sum())
        if agg.distinct:
            if agg.func not in (AF.COUNT, AF.SUM, AF.AVG, AF.MIN, AF.MAX):
                raise HostUnsupported(f"distinct {agg.func}")
            vals = c.values[valid]
            if c.dtype.is_string and c.dict is not None:
                code = vals.astype(np.int64)
            else:
                code = np.unique(vals, return_inverse=True)[1]
            pair = vinv * (int(code.max()) + 1 if len(code) else 1) + code
            _, first = np.unique(pair, return_index=True)
            vinv, vals = vinv[first], vals[first]
            n_valid = len(first)
            c = replace(c, values=vals)
        else:
            vals = c.values[valid]
        if agg.func is AF.COUNT:
            cnt = np.bincount(vinv, minlength=k).astype(np.int64)
            return HCol(out_dtype, cnt, None)
        counts = np.bincount(vinv, minlength=k)
        empty = counts == 0
        if agg.func in (AF.SUM, AF.AVG):
            if c.dtype.is_string:
                raise HostUnsupported("sum over strings")
            if vals.dtype.kind == "f":
                s = np.bincount(vinv, weights=vals, minlength=k)
            elif len(vals) == 0 or (len(vals) * max(abs(int(vals.max())),
                                                    abs(int(vals.min())),
                                                    1)) < (1 << 53):
                # every possible partial sum fits float64 exactly: bincount's
                # C loop beats np.add.at's per-element ufunc dispatch ~10x
                s = np.bincount(vinv, weights=vals.astype(np.float64),
                                minlength=k).astype(np.int64)
            else:
                s = np.zeros(k, dtype=np.int64)
                np.add.at(s, vinv, vals.astype(np.int64))
            if agg.func is AF.AVG:
                out = s / np.maximum(counts, 1)
                return HCol(out_dtype, out.astype(np.float64),
                            empty if empty.any() else None)
            out = s.astype(out_dtype.device_dtype())
            return HCol(out_dtype, out, empty if empty.any() else None)
        # MIN / MAX via sort + reduceat-style first/last per group
        if c.dtype.is_string and c.dict is not None:
            ranks = c.dict.ranks().astype(np.int64)
            sortv = ranks[np.clip(vals, 0, max(len(c.dict) - 1, 0))] \
                if len(c.dict) else np.zeros(len(vals), np.int64)
        else:
            sortv = vals
        order = np.lexsort((sortv, vinv))
        sv, si = vinv[order], vals[order]
        bnd = np.ones(len(sv), dtype=bool)
        if len(sv):
            bnd[1:] = sv[1:] != sv[:-1]
        out = np.zeros(k, dtype=vals.dtype)
        if len(sv):
            if agg.func is AF.MIN:
                out[sv[bnd]] = si[bnd]
            else:
                last = np.roll(bnd, -1)
                out[sv[last]] = si[last]
        return HCol(out_dtype, out, empty if empty.any() else None, c.dict)

    # ---- join ------------------------------------------------------------

    def _key_codes(self, lcols: list, rcols: list, nl: int, nr: int):
        """Shared int64 encoding of the two sides' key tuples.
        Returns (lkey, rkey, lvalid, rvalid)."""
        lparts, rparts = [], []
        lvalid = np.ones(nl, dtype=bool)
        rvalid = np.ones(nr, dtype=bool)
        for lc, rc in zip(lcols, rcols):
            if lc.dtype.is_string or rc.dtype.is_string:
                # join string keys on BOTH per-entry hashes (seed 0 + seed 1,
                # 128-bit effective — the device join's collision guard,
                # exec/batch.py DictInfo)
                for attr in ("hashes", "hashes2"):
                    lv = _str_hash_lane(lc, nl, attr)
                    rv = _str_hash_lane(rc, nr, attr)
                    lparts.append(lv)
                    rparts.append(rv)
                if lc.nulls is not None:
                    lvalid &= ~lc.nulls
                if rc.nulls is not None:
                    rvalid &= ~rc.nulls
                continue
            else:
                lv, rv = lc.values, rc.values
                if lv.dtype.kind == "f" or rv.dtype.kind == "f":
                    lv = lv.astype(np.float64).view(np.int64)
                    rv = rv.astype(np.float64).view(np.int64)
                else:
                    lv = lv.astype(np.int64)
                    rv = rv.astype(np.int64)
            lparts.append(lv)
            rparts.append(rv)
            if lc.nulls is not None:
                lvalid &= ~lc.nulls
            if rc.nulls is not None:
                rvalid &= ~rc.nulls
        if len(lparts) == 1:
            return lparts[0], rparts[0], lvalid, rvalid
        both = np.concatenate(
            [np.stack(lparts, axis=1), np.stack(rparts, axis=1)], axis=0)
        _, inv = np.unique(both, axis=0, return_inverse=True)
        return inv[:nl].astype(np.int64), inv[nl:].astype(np.int64), \
            lvalid, rvalid

    def _probe(self, lkey, rkey, lval, rval):
        """Probe phase -> (cnt[left], lo[left], rpos): row i of the left
        matches build rows rpos[lo[i] : lo[i]+cnt[i]].

        Dense build-key ranges use a counting-sort direct probe (the host
        analog of the device's direct array join, exec/join.py direct_probe):
        O(n + range) with no comparison sort. Sparse ranges fall back to
        sort + searchsorted, with a single-probe shortcut when the build keys
        are unique (every TPC-H PK side)."""
        rv = rkey[rval]
        rpos_all = np.nonzero(rval)[0]
        n_build = len(rv)
        if n_build == 0:
            return (np.zeros(len(lkey), dtype=np.int64),
                    np.zeros(len(lkey), dtype=np.int64),
                    np.zeros(0, dtype=np.int64))
        lo_k, hi_k = int(rv.min()), int(rv.max())
        rng = hi_k - lo_k + 1
        if 0 < rng <= max(1 << 22, 4 * n_build):
            codes = rv - lo_k
            counts = np.bincount(codes, minlength=rng)
            starts = np.zeros(rng + 1, dtype=np.int64)
            np.cumsum(counts, out=starts[1:])
            order = np.argsort(codes, kind="stable")
            rpos = rpos_all[order]
            in_range = lval & (lkey >= lo_k) & (lkey <= hi_k)
            lc = np.where(in_range, lkey - lo_k, 0)
            cnt = np.where(in_range, counts[lc], 0)
            lo = np.where(in_range, starts[:-1][lc], 0)
            return cnt, lo, rpos
        order = np.argsort(rv, kind="stable")
        rpos = rpos_all[order]
        rsorted = rv[order]
        lo = np.searchsorted(rsorted, lkey, side="left")
        unique_build = n_build < 2 or \
            bool((rsorted[1:] != rsorted[:-1]).all())
        if unique_build:
            safe = np.clip(lo, 0, n_build - 1)
            cnt = np.where(lval & (rsorted[safe] == lkey), 1, 0)
        else:
            hi = np.searchsorted(rsorted, lkey, side="right")
            cnt = np.where(lval, hi - lo, 0)
        return cnt.astype(np.int64), lo, rpos

    # --- inner-join chain reorder ----------------------------------------

    def _flatten_inner(self, plan: L.Join):
        """Flatten a left-deep INNER equi-join spine whose keys are all plain
        column refs -> (rels, edges, residuals); None when the shape doesn't
        apply. Edges/residual column indexes are global (the spine is
        left-deep, so each node's concat schema is a prefix)."""
        rels: list = []
        edges: list = []      # (global left col, global right col)
        residuals: list = []  # exprs over the full concat schema

        def rec(node) -> bool:
            if isinstance(node, L.Join) and node.join_type is JoinType.INNER \
                    and node.left_keys and \
                    all(isinstance(k, E.Column) for k in
                        node.left_keys + node.right_keys):
                if not rec(node.left):
                    return False
                lw = len(node.left.schema)
                rels.append(node.right)
                for lk, rk in zip(node.left_keys, node.right_keys):
                    edges.append((lk.index, lw + rk.index))
                if node.residual is not None:
                    residuals.append(node.residual)
                return True
            rels.append(node)
            return True

        if not rec(plan):
            return None
        return (rels, edges, residuals) if len(rels) >= 3 else None

    def _exec_inner_chain(self, plan: L.Join, flat) -> HBatch:
        """Execute a flattened inner-join chain smallest-connected-first with
        EXACT input cardinalities (an optimizer would estimate; the host tier
        has the real numbers in hand). Yields the same rows as the written
        order; column order is restored at the end (no copy — HCol lists
        permute by reference)."""
        rels, edges, residuals = flat
        batches = [self._exec(r) for r in rels]
        offsets, off = [], 0
        for r in rels:
            offsets.append(off)
            off += len(r.schema)

        def rel_of(g: int) -> int:
            for i in range(len(rels) - 1, -1, -1):
                if g >= offsets[i]:
                    return i
            return 0

        order = [int(np.argmin([b.n for b in batches]))]
        remaining = [i for i in range(len(rels)) if i not in order]
        while remaining:
            conn = [i for i in remaining
                    if any(rel_of(a) in order and rel_of(bb) == i or
                           rel_of(bb) in order and rel_of(a) == i
                           for a, bb in edges)]
            pool = conn or remaining  # disconnected: cross join (guarded)
            nxt = min(pool, key=lambda i: batches[i].n)
            order.append(nxt)
            remaining.remove(nxt)

        # run the chain; cur maps global col idx -> position in cur batch
        placed = {order[0]}
        cur = batches[order[0]]
        pos = {offsets[order[0]] + k: k
               for k in range(len(rels[order[0]].schema))}
        consumed = [False] * len(edges)
        for i in order[1:]:
            rb = batches[i]
            lkeys, rkeys = [], []
            for ei, (a, bb) in enumerate(edges):
                if consumed[ei]:
                    continue
                if rel_of(a) in placed and rel_of(bb) == i:
                    lkeys.append(cur.cols[pos[a]])
                    rkeys.append(rb.cols[bb - offsets[i]])
                    consumed[ei] = True
                elif rel_of(bb) in placed and rel_of(a) == i:
                    lkeys.append(cur.cols[pos[bb]])
                    rkeys.append(rb.cols[a - offsets[i]])
                    consumed[ei] = True
            if lkeys:
                lkey, rkey, lval, rval = self._key_codes(
                    lkeys, rkeys, cur.n, rb.n)
                # build on the SMALLER side (the probe pays O(probe) passes,
                # the build pays the argsort)
                if rb.n <= cur.n:
                    cnt, lo, rpos = self._probe(lkey, rkey, lval, rval)
                    total = int(cnt.sum())
                    lidx = np.repeat(np.arange(cur.n), cnt)
                    starts = np.repeat(lo, cnt)
                    offs = np.arange(total) - np.repeat(
                        np.cumsum(cnt) - cnt, cnt)
                    ridx = rpos[starts + offs]
                else:
                    cnt, lo, rpos = self._probe(rkey, lkey, rval, lval)
                    total = int(cnt.sum())
                    ridx = np.repeat(np.arange(rb.n), cnt)
                    starts = np.repeat(lo, cnt)
                    offs = np.arange(total) - np.repeat(
                        np.cumsum(cnt) - cnt, cnt)
                    lidx = rpos[starts + offs]
            else:
                if cur.n * rb.n > self._CROSS_LIMIT:
                    raise HostUnsupported("cross join too large")
                lidx = np.repeat(np.arange(cur.n), rb.n)
                ridx = np.tile(np.arange(rb.n), cur.n)
            cur = _join_output(None, cur, rb, lidx, ridx, None, None)
            base = len(pos)
            for k in range(len(rels[i].schema)):
                pos[offsets[i] + k] = base + k
            placed.add(i)
        # cyclic edges never consumed at placement: equality post-filters
        for ei, (a, bb) in enumerate(edges):
            if not consumed[ei]:
                ca, cb = cur.cols[pos[a]], cur.cols[pos[bb]]
                eq = self._numeric_binary(E.BinOp.EQ, ca, cb, None, cur) \
                    if not ca.dtype.is_string else \
                    self._string_compare(E.BinOp.EQ, ca, cb, cur)
                cur = cur.mask(eq.values & _valid(cur.n, eq.nulls))
        # restore written column order (plan.schema) by list permutation
        cols = [cur.cols[pos[g]] for g in range(off)]
        out = HBatch(plan.schema, cols, cur.n)
        for res in residuals:
            v, nulls = self._eval_bool(res, out)
            out = out.mask(v & _valid(out.n, nulls))
        tracing.counter("host.chain_reorder")
        return out

    def _exec_join(self, plan: L.Join) -> HBatch:
        if plan.join_type is JoinType.INNER:
            flat = self._flatten_inner(plan)
            if flat is not None:
                return self._exec_inner_chain(plan, flat)
        lb = self._exec(plan.left)
        rb = self._exec(plan.right)
        jt = plan.join_type
        if jt is JoinType.CROSS or not plan.left_keys:
            if lb.n * rb.n > self._CROSS_LIMIT:
                raise HostUnsupported("cross join too large")
            lidx = np.repeat(np.arange(lb.n), rb.n)
            ridx = np.tile(np.arange(rb.n), lb.n)
            out = _join_output(plan.schema, lb, rb, lidx, ridx, None, None)
            if plan.residual is not None:
                v, nulls = self._eval_bool(plan.residual, out)
                out = out.mask(v & _valid(out.n, nulls))
            return out
        lk = [self._eval_col(e, lb, e.dtype) for e in plan.left_keys]
        rk = [self._eval_col(e, rb, e.dtype) for e in plan.right_keys]
        lkey, rkey, lval, rval = self._key_codes(lk, rk, lb.n, rb.n)
        cnt, lo, rpos = self._probe(lkey, rkey, lval, rval)
        total = int(cnt.sum())
        lidx = np.repeat(np.arange(lb.n), cnt)
        starts = np.repeat(lo, cnt)
        offs = np.arange(total) - np.repeat(np.cumsum(cnt) - cnt, cnt)
        ridx = rpos[starts + offs]
        if plan.residual is not None:
            pairs = _join_output(plan.schema if jt is JoinType.INNER else None,
                                 lb, rb, lidx, ridx, None, None)
            v, nulls = self._eval_bool(plan.residual, pairs)
            keep = v & _valid(pairs.n, nulls)
            lidx, ridx = lidx[keep], ridx[keep]
        if jt in (JoinType.INNER,):
            return _join_output(plan.schema, lb, rb, lidx, ridx, None, None)
        lmatched = np.zeros(lb.n, dtype=bool)
        lmatched[lidx] = True
        if jt is JoinType.SEMI:
            return lb.take(np.nonzero(lmatched)[0])
        if jt is JoinType.ANTI:
            return lb.take(np.nonzero(~lmatched)[0])
        rmatched = np.zeros(rb.n, dtype=bool)
        rmatched[ridx] = True
        if jt in (JoinType.LEFT, JoinType.FULL):
            extra = np.nonzero(~lmatched)[0]
            lidx = np.concatenate([lidx, extra])
            ridx = np.concatenate([ridx, np.full(len(extra), -1)])
        if jt in (JoinType.RIGHT, JoinType.FULL):
            extra = np.nonzero(~rmatched)[0]
            lidx = np.concatenate([lidx, np.full(len(extra), -1)])
            ridx = np.concatenate([ridx, extra])
        return _join_output(plan.schema, lb, rb, lidx, ridx,
                            lidx < 0, ridx < 0)

    # ---- expressions -----------------------------------------------------

    def _eval_bool(self, e: E.Expr, b: HBatch):
        c = self._eval(e, b)
        v = c.values
        if v.dtype != np.bool_:
            v = v.astype(bool)
        return v, c.nulls

    def _eval_col(self, e: E.Expr, b: HBatch, dtype) -> HCol:
        c = self._eval(e, b)
        want = (dtype or c.dtype)
        if want is not None and not want.is_string and \
                c.values.dtype != want.device_dtype():
            c = replace(c, values=c.values.astype(want.device_dtype()),
                        dtype=want)
        return c

    def _eval(self, e: E.Expr, b: HBatch) -> HCol:
        m = getattr(self, "_e_" + type(e).__name__.lower(), None)
        if m is None:
            raise HostUnsupported(f"expr {type(e).__name__}")
        return m(e, b)

    def _e_alias(self, e: E.Alias, b):
        return self._eval(e.operand, b)

    def _e_column(self, e: E.Column, b: HBatch):
        if e.index is None:
            raise PlanError(f"unbound column {e.name}")
        return b.cols[e.index]

    def _e_literal(self, e: E.Literal, b: HBatch):
        dtype = e.dtype or e.literal_type
        v = e.value
        if v is None:
            dd = (dtype or T.INT64)
            lane = np.int32 if dd.is_string else dd.device_dtype()
            return HCol(dtype or T.INT64, np.zeros(b.n, dtype=lane),
                        np.ones(b.n, dtype=bool),
                        DictInfo.from_values([]) if dd.is_string else None)
        if dtype is not None and dtype.is_string:
            d = DictInfo.from_values([str(v)])
            return HCol(dtype, np.zeros(b.n, dtype=np.int32), None, d)
        if isinstance(v, bool):
            return HCol(T.BOOL, np.full(b.n, v, dtype=bool), None)
        lane = (dtype or (T.INT64 if isinstance(v, int) else T.FLOAT64)) \
            .device_dtype()
        return HCol(dtype or (T.INT64 if isinstance(v, int) else T.FLOAT64),
                    np.full(b.n, v, dtype=lane), None)

    def _e_scalarsubquery(self, e: E.ScalarSubquery, b: HBatch):
        memo = getattr(e, "_host_lit", None)
        if memo is None:
            if not isinstance(e.query, L.LogicalPlan):
                raise PlanError("unbound scalar subquery reached executor")
            t = self.execute_to_arrow(e.query)
            if t.num_rows > 1:
                raise ExecError("scalar subquery returned more than one row")
            dtype = e.query.schema.fields[0].dtype
            val = None if t.num_rows == 0 else t.column(0)[0].as_py()
            if dtype.id == T.TypeId.DATE32 and val is not None:
                import datetime as _dt
                val = val.toordinal() - _dt.date(1970, 1, 1).toordinal()
            elif dtype.id == T.TypeId.TIMESTAMP and val is not None:
                import datetime as _dt
                val = (val - _dt.datetime(1970, 1, 1)) \
                    // _dt.timedelta(microseconds=1)
            lit = E.Literal(value=val, literal_type=dtype)
            lit.dtype = e.dtype or dtype
            e._host_lit = lit
            memo = lit
        return self._e_literal(memo, b)

    def _e_binary(self, e: E.Binary, b: HBatch):
        op = e.op
        if op in (E.BinOp.AND, E.BinOp.OR):
            lv, ln = self._eval_bool(e.left, b)
            rv, rn = self._eval_bool(e.right, b)
            lN = ln if ln is not None else np.zeros(b.n, bool)
            rN = rn if rn is not None else np.zeros(b.n, bool)
            if op is E.BinOp.AND:  # Kleene: F dominates, T&T=T, else NULL
                known_true = (lv & ~lN) & (rv & ~rN)
                known_false = (~lv & ~lN) | (~rv & ~rN)
            else:                  # Kleene: T dominates, F|F=F, else NULL
                known_true = (lv & ~lN) | (rv & ~rN)
                known_false = (~lv & ~lN) & (~rv & ~rN)
            nulls = ~(known_true | known_false)
            return HCol(T.BOOL, known_true,
                        nulls if nulls.any() else None)
        lc = self._eval(e.left, b)
        rc = self._eval(e.right, b)
        if lc.dtype.is_string or rc.dtype.is_string:
            return self._string_compare(op, lc, rc, b)
        return self._numeric_binary(op, lc, rc, e.dtype, b)

    def _numeric_binary(self, op, lc: HCol, rc: HCol, out_dtype, b: HBatch):
        if op in E.COMPARISONS:
            res_dtype = T.BOOL
            wd = T.common_type(lc.dtype, rc.dtype).device_dtype()
        else:
            res_dtype = out_dtype or T.common_type(lc.dtype, rc.dtype)
            wd = res_dtype.device_dtype()
        lv, rv = lc.values, rc.values
        if lc.dtype.id == T.TypeId.DATE32 and rc.dtype.id == T.TypeId.TIMESTAMP:
            lv = lv.astype(np.int64) * np.int64(86_400_000_000)
        if rc.dtype.id == T.TypeId.DATE32 and lc.dtype.id == T.TypeId.TIMESTAMP:
            rv = rv.astype(np.int64) * np.int64(86_400_000_000)
        lv = lv.astype(wd) if lv.dtype != wd else lv
        rv = rv.astype(wd) if rv.dtype != wd else rv
        nulls = _or_nulls(lc.nulls, rc.nulls)
        B = E.BinOp
        if op is B.ADD:
            out = lv + rv
        elif op is B.SUB:
            out = lv - rv
        elif op is B.MUL:
            out = lv * rv
        elif op is B.DIV:
            zero = rv == 0
            safe = np.where(zero, 1, rv)
            if res_dtype.is_integer:
                out = np.trunc(lv.astype(np.float64) /
                               safe.astype(np.float64)).astype(wd)
            else:
                out = lv / safe
            out = np.where(zero, 0, out)
            nulls = _or_nulls(nulls, zero if zero.any() else None)
        elif op is B.MOD:
            zero = rv == 0
            safe = np.where(zero, 1, rv)
            out = lv - np.trunc(lv.astype(np.float64) /
                                safe.astype(np.float64)).astype(wd) * safe
            nulls = _or_nulls(nulls, zero if zero.any() else None)
        elif op is B.EQ:
            out = lv == rv
        elif op is B.NEQ:
            out = lv != rv
        elif op is B.LT:
            out = lv < rv
        elif op is B.LTE:
            out = lv <= rv
        elif op is B.GT:
            out = lv > rv
        else:
            out = lv >= rv
        return HCol(res_dtype, out, nulls)

    def _string_compare(self, op, lc: HCol, rc: HCol, b: HBatch):
        if op not in E.COMPARISONS:
            raise HostUnsupported(f"string {op}")
        ls = _materialize_str(lc)
        rs = _materialize_str(rc)
        B = E.BinOp
        out = {B.EQ: ls == rs, B.NEQ: ls != rs, B.LT: ls < rs,
               B.LTE: ls <= rs, B.GT: ls > rs, B.GTE: ls >= rs}[op]
        return HCol(T.BOOL, out, _or_nulls(lc.nulls, rc.nulls))

    def _e_not(self, e: E.Not, b):
        v, nulls = self._eval_bool(e.operand, b)
        return HCol(T.BOOL, ~v, nulls)

    def _e_negate(self, e: E.Negate, b):
        c = self._eval(e.operand, b)
        return replace(c, values=-c.values)

    def _e_isnull(self, e: E.IsNull, b):
        c = self._eval(e.operand, b)
        isn = c.nulls if c.nulls is not None else np.zeros(b.n, dtype=bool)
        return HCol(T.BOOL, ~isn if e.negated else isn.copy(), None)

    _US_PER_DAY = 86_400_000_000

    def _e_cast(self, e: E.Cast, b):
        c = self._eval(e.operand, b)
        to = e.to
        if to.is_string or c.dtype.is_string:
            raise HostUnsupported("string cast")
        v = c.values
        # lane-unit rescale (device parity: expr_compile date<->timestamp)
        if c.dtype.id == T.TypeId.DATE32 and to.id == T.TypeId.TIMESTAMP:
            v = v.astype(np.int64) * np.int64(self._US_PER_DAY)
        elif c.dtype.id == T.TypeId.TIMESTAMP and to.id == T.TypeId.DATE32:
            v = np.floor_divide(v, np.int64(self._US_PER_DAY))
        return HCol(to, v.astype(to.device_dtype()), c.nulls)

    def _e_case(self, e: E.Case, b):
        out_dtype = e.dtype
        if out_dtype is not None and out_dtype.is_string:
            raise HostUnsupported("string case")
        lane = (out_dtype or T.FLOAT64).device_dtype()
        out = np.zeros(b.n, dtype=lane)
        nulls = np.ones(b.n, dtype=bool)  # unset lanes -> ELSE below
        decided = np.zeros(b.n, dtype=bool)
        for cond, val in e.whens:
            cv, cn = self._eval_bool(cond, b)
            hit = cv & _valid(b.n, cn) & ~decided
            vc = self._eval_col(val, b, out_dtype)
            out[hit] = vc.values[hit]
            nulls[hit] = vc.nulls[hit] if vc.nulls is not None else False
            decided |= hit
        rest = ~decided
        if e.else_ is not None and rest.any():
            vc = self._eval_col(e.else_, b, out_dtype)
            out[rest] = vc.values[rest]
            nulls[rest] = vc.nulls[rest] if vc.nulls is not None else False
        return HCol(out_dtype or T.FLOAT64, out,
                    nulls if nulls.any() else None)

    def _e_inlist(self, e: E.InList, b):
        c = self._eval(e.operand, b)
        items = []
        has_null = False
        for it in e.items:
            if not isinstance(it, E.Literal):
                raise HostUnsupported("non-literal IN list")
            if it.value is None:
                has_null = True  # NULL in the list: misses become UNKNOWN
            else:
                items.append(it.value)
        if c.dtype.is_string:
            if c.dict is not None:
                lut = np.isin(c.dict.values.astype(str),
                              np.asarray([str(i) for i in items], dtype=str)) \
                    if items and len(c.dict) else \
                    np.zeros(max(len(c.dict), 1), dtype=bool)
                out = lut[np.clip(c.values, 0, max(len(c.dict) - 1, 0))] \
                    if len(c.dict) else np.zeros(b.n, dtype=bool)
            else:
                sv = _materialize_str(c)
                out = np.isin(sv, np.asarray([str(i) for i in items],
                                             dtype=str)) \
                    if items else np.zeros(b.n, dtype=bool)
        else:
            out = np.isin(c.values,
                          np.asarray(items, dtype=c.values.dtype)) \
                if items else np.zeros(b.n, dtype=bool)
        nulls = c.nulls
        if has_null:
            # x IN (..., NULL): no match -> NULL, match -> TRUE (3VL);
            # negated NOT IN with a NULL never returns TRUE for non-matches
            miss_null = ~out
            nulls = _or_nulls(nulls, miss_null if miss_null.any() else None)
        if e.negated:
            out = ~out
        return HCol(T.BOOL, out, nulls)

    def _e_like(self, e: E.Like, b):
        c = self._eval(e.operand, b)
        if c.dict is not None:
            lut = _like_lut(c.dict, e.pattern, e.case_insensitive)
            out = lut[np.clip(c.values, 0, max(len(c.dict) - 1, 0))] \
                if len(c.dict) else np.zeros(b.n, dtype=bool)
        else:
            out = _vector_match(_materialize_str(c), e.pattern,
                                e.case_insensitive)
        if e.negated:
            out = ~out
        return HCol(T.BOOL, out, c.nulls)

    def _e_func(self, e: E.Func, b):
        name = e.name.lower()
        if name in ("year", "month", "day",
                    "extract_year", "extract_month", "extract_day"):
            which = name.split("_")[-1]
            c = self._eval(e.args[0], b)
            days = c.values
            if c.dtype.id == T.TypeId.TIMESTAMP:
                days = np.floor_divide(days, np.int64(86_400_000_000)) \
                    .astype(np.int32)
            y, m, d = _civil_from_days(days)
            return HCol(T.INT32, {"year": y, "month": m, "day": d}[which],
                        c.nulls)
        if name in _HOST_STR_FUNCS:
            return self._string_func(name, e, b)
        unary = {"abs": np.abs, "floor": np.floor, "ceil": np.ceil,
                 "sqrt": np.sqrt, "exp": np.exp, "ln": np.log,
                 "log": np.log, "log10": np.log10, "sign": np.sign}
        if name in unary:
            c = self._eval(e.args[0], b)
            out_dtype = e.dtype
            return HCol(out_dtype,
                        unary[name](c.values.astype(out_dtype.device_dtype())),
                        c.nulls)
        if name == "round":
            c = self._eval(e.args[0], b)
            digits = 0
            if len(e.args) > 1:
                if not isinstance(e.args[1], E.Literal):
                    raise HostUnsupported("round with non-literal digits")
                digits = int(e.args[1].value)
            scale = 10.0 ** digits
            return HCol(T.FLOAT64,
                        np.round(c.values.astype(np.float64) * scale) / scale,
                        c.nulls)
        if name == "coalesce":
            out_dtype = e.dtype
            if out_dtype is not None and out_dtype.is_string:
                raise HostUnsupported("string coalesce")
            out = None
            nulls = None
            for a in e.args:
                c = self._eval_col(a, b, out_dtype)
                if out is None:
                    out = c.values.copy()
                    nulls = (c.nulls.copy() if c.nulls is not None
                             else np.zeros(b.n, dtype=bool))
                else:
                    take = nulls & _valid(b.n, c.nulls)
                    out[take] = c.values[take]
                    nulls &= ~take
            return HCol(out_dtype or T.FLOAT64, out,
                        nulls if nulls is not None and nulls.any() else None)
        raise HostUnsupported(f"function {name}")

    def _string_func(self, name: str, e: E.Func, b: HBatch):
        c = self._eval(e.args[0], b)
        if c.dict is None:
            raise HostUnsupported("string fn on non-dictionary value")
        d = c.dict

        def lit_int(i, default=None):
            if i >= len(e.args):
                if default is not None:
                    return default
                raise HostUnsupported(f"{name} missing arg")
            a = e.args[i]
            if not isinstance(a, E.Literal):
                raise HostUnsupported(f"{name} non-literal arg")
            return int(a.value)

        if name in ("length", "char_length", "character_length"):
            lut = np.fromiter((len(str(v)) for v in d.values),
                              dtype=np.int64, count=len(d))
            out = lut[np.clip(c.values, 0, max(len(d) - 1, 0))] \
                if len(d) else np.zeros(b.n, np.int64)
            return HCol(T.INT64, out, c.nulls)

        def transform(f: Callable[[str], str], memo_key=None) -> HCol:
            # per-entry transforms memoize on the (cached) DictInfo: the
            # same substring/upper over the same column costs one python
            # pass per PROCESS, not one per evaluation (q22 evaluates
            # substr(c_phone,1,2) three times over a 150k-entry dictionary)
            cache = getattr(d, "_xform_memo", None)
            if cache is None:
                cache = {}
                object.__setattr__(d, "_xform_memo", cache)
            hit = cache.get(memo_key) if memo_key is not None else None
            if hit is None:
                new = np.asarray([f(str(v)) for v in d.values], dtype=object)
                uniq, inverse = (np.unique(new.astype(str),
                                           return_inverse=True)
                                 if len(new) else (np.asarray([], dtype=str),
                                                   np.zeros(0, np.int64)))
                nd = DictInfo.from_values(uniq.astype(object))
                hit = (inverse.astype(np.int32), nd)
                if memo_key is not None:
                    cache[memo_key] = hit
            inverse32, nd = hit
            codes = inverse32[np.clip(c.values, 0, max(len(d) - 1, 0))] \
                if len(d) else np.zeros(b.n, np.int32)
            return HCol(T.STRING, codes, c.nulls, nd)

        if name == "upper":
            return transform(str.upper, memo_key=("upper",))
        if name == "lower":
            return transform(str.lower, memo_key=("lower",))
        if name == "capitalize":
            # reference parity: crates/engine/src/lib.rs:71-95
            return transform(lambda s: (s[:1].upper() + s[1:].lower())
                             if s else s, memo_key=("capitalize",))
        if name == "trim":
            return transform(str.strip, memo_key=("trim",))
        if name in ("substr", "substring"):
            start = lit_int(1)
            ln = lit_int(2, default=1 << 30)
            i0 = max(start - 1, 0)
            return transform(lambda s: s[i0: i0 + ln],
                             memo_key=("substr", i0, ln))
        if name == "left":
            ln = lit_int(1)
            return transform(lambda s: s[:ln], memo_key=("left", ln))
        if name == "right":
            ln = lit_int(1)
            return transform(lambda s: s[-ln:] if ln else "",
                             memo_key=("right", ln))
        if name == "concat":
            parts = [self._eval(a, b) for a in e.args]
            svals = [_materialize_str(p) if p.dtype.is_string
                     else p.values.astype(str) for p in parts]
            joined = svals[0]
            for s in svals[1:]:
                joined = np.char.add(joined, s)
            uniq, inverse = np.unique(joined, return_inverse=True)
            nd = DictInfo.from_values(uniq.astype(object))
            nulls = None
            for p in parts:
                nulls = _or_nulls(nulls, p.nulls)
            return HCol(T.STRING, inverse.astype(np.int32), nulls, nd)
        raise HostUnsupported(f"string function {name}")


_HOST_STR_FUNCS = {"upper", "lower", "capitalize", "trim", "substr",
                   "substring", "length", "char_length", "character_length",
                   "concat", "left", "right"}


def _serve_by_name(stored: HBatch, want: T.Schema) -> Optional[HBatch]:
    """Project a memoized batch down to a narrower requested schema by column
    NAME; None when names are missing or ambiguous (duplicate names)."""
    names = [f.name for f in stored.schema.fields]
    idx = {}
    for i, nm in enumerate(names):
        if nm in idx:
            idx[nm] = None  # ambiguous
        else:
            idx[nm] = i
    cols = []
    for f in want.fields:
        i = idx.get(f.name)
        if i is None:
            return None
        c = stored.cols[i]
        if c.dtype != f.dtype:
            return None
        cols.append(c)
    return HBatch(want, cols, stored.n)


def _hash_str(sv: np.ndarray, seed: int = 0) -> np.ndarray:
    from igloo_tpu.exec.batch import hash64_bytes
    return hash64_bytes(np.asarray(sv, dtype=object), seed=seed) \
        .view(np.int64)


def _str_hash_lane(c: HCol, n: int, attr: str) -> np.ndarray:
    """Per-row 64-bit hash lane of a string column (gathered through the
    dictionary when present)."""
    if c.dict is not None:
        if not len(c.dict):
            return np.zeros(n, dtype=np.int64)
        h = getattr(c.dict, attr)
        return h[np.clip(c.values, 0, len(c.dict) - 1)].view(np.int64)
    return _hash_str(_materialize_str(c), seed=0 if attr == "hashes" else 1)


def _join_output(schema, lb: HBatch, rb: HBatch, lidx, ridx,
                 lnull, rnull) -> HBatch:
    """Concatenate gathered left+right columns; negative idx lanes (outer-join
    unmatched) become null."""
    cols = []
    for b_, idx, pad in ((lb, lidx, lnull), (rb, ridx, rnull)):
        safe = np.where(idx < 0, 0, idx)
        for c in b_.cols:
            vals = c.values[safe] if b_.n else np.zeros(
                len(idx), dtype=c.values.dtype)
            nulls = c.nulls[safe] if (c.nulls is not None and b_.n) else None
            if pad is not None and pad.any():
                nulls = (nulls.copy() if nulls is not None
                         else np.zeros(len(idx), dtype=bool))
                nulls[pad] = True
            cols.append(HCol(c.dtype, vals, nulls, c.dict))
    out_schema = schema
    if out_schema is None:
        out_schema = T.Schema(list(lb.schema.fields) + list(rb.schema.fields))
    return HBatch(out_schema, cols, len(lidx))


def _pa_for(dtype: T.DataType) -> pa.DataType:
    from igloo_tpu.exec.batch import dtype_to_arrow
    return dtype_to_arrow(dtype)


def to_arrow(b: HBatch) -> pa.Table:
    arrays, fields = [], []
    for f, c in zip(b.schema, b.cols):
        nulls = c.nulls
        if f.dtype.is_string:
            if c.dict is not None and len(c.dict):
                py = c.dict.values[np.clip(c.values, 0, len(c.dict) - 1)]
            else:
                py = np.full(b.n, "", dtype=object)
            if nulls is not None:
                py = py.copy()
                py[nulls] = None
            arrays.append(pa.array(py, type=pa.string()))
        elif f.dtype.id == T.TypeId.DATE32:
            a = pa.array(c.values.astype("int32"),
                         type=pa.int32()).cast(pa.date32())
            if nulls is not None:
                a = pa.compute.if_else(pa.array(~nulls), a,
                                       pa.scalar(None, type=pa.date32()))
            arrays.append(a)
        elif f.dtype.id == T.TypeId.TIMESTAMP:
            a = pa.array(c.values.astype("int64"),
                         type=pa.int64()).cast(pa.timestamp("us"))
            if nulls is not None:
                a = pa.compute.if_else(
                    pa.array(~nulls), a,
                    pa.scalar(None, type=pa.timestamp("us")))
            arrays.append(a)
        else:
            arrays.append(pa.array(c.values, mask=nulls))
        fields.append(pa.field(f.name, arrays[-1].type, f.nullable))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))
