"""Window-function kernel: one jit-traceable segmented-scan pass.

The reference executes window functions through DataFusion's engine
(crates/engine/src/lib.rs:54-57 — its custom operators have no window
support). TPU design, all static shapes:

    sort rows by (partition keys, order keys)  ->  contiguous partitions
    -> per-row positions + peer-group boundaries from lane comparisons
    -> ranks / running aggregates as cumsum differences and segmented scans
       (gathers only on the hot paths — no full-capacity scatters)
    -> inverse permutation restores the original row order

Semantics: with ORDER BY, aggregates use the SQL default frame (RANGE
UNBOUNDED PRECEDING .. CURRENT ROW): peers — rows tied on the order keys —
share the value at the END of their peer group. Without ORDER BY the frame is
the whole partition. MIN/MAX running variants use a segmented associative
scan; NULL arguments are skipped (do not contribute), and COUNT counts only
non-null arguments.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from igloo_tpu import types as T
from igloo_tpu.exec import kernels as K
from igloo_tpu.exec.batch import DeviceBatch, DeviceColumn
from igloo_tpu.exec.expr_compile import Compiled, Env
from igloo_tpu.plan.expr import AggFunc


@dataclass(frozen=True)
class WinSpec:
    """One compiled window function over the node's shared OVER spec."""
    kind: str                      # row_number|rank|dense_rank|lag|lead|agg
    agg_func: Optional[AggFunc] = None
    arg: Optional[Compiled] = None       # agg argument / lag-lead value
    offset: int = 1                      # lag/lead
    out_dtype: T.DataType = T.INT64


def compile_window(plan, comp, resolve) -> tuple:
    """Shared host-side compile for a L.Window node (staged executor + fused
    compiler): returns (fingerprint_parts, part_keys, order_keys, specs,
    out_dicts, out_bounds) where out_dicts/bounds cover ONLY the appended
    window columns. `resolve` is the executor's scalar-subquery resolver."""
    from igloo_tpu.errors import NotSupportedError
    from igloo_tpu.exec.expr_compile import rank_lane
    pres = [resolve(e) for e in plan.partition_exprs]
    ores = [resolve(e) for e in plan.order_exprs]
    part_keys = [comp.compile(e) for e in pres]
    order_keys = [comp.compile(e) for e in ores]
    # ORDER over unsorted (high-cardinality) dictionaries sorts ranks
    order_keys = [rank_lane(k, comp) if k.dtype.is_string else k
                  for k in order_keys]
    specs: list[WinSpec] = []
    out_dicts: list = []
    out_bounds: list = []
    fps: list = []
    for w in plan.funcs:
        if w.func == "agg":
            a = w.agg
            arg = None
            if a.arg is not None:
                r = resolve(a.arg)
                arg = comp.compile(r)
                fps.append(repr(r))
                if arg.dtype.is_string:
                    raise NotSupportedError(
                        "string arguments to windowed aggregates are not "
                        "supported yet")
            specs.append(WinSpec("agg", a.func, arg, out_dtype=w.dtype))
            fps.append(("agg", a.func, w.dtype))
            out_dicts.append(None)
        elif w.func in ("lag", "lead"):
            r = resolve(w.args[0])
            arg = comp.compile(r)
            offset = int(w.args[1].value) if len(w.args) > 1 else 1
            specs.append(WinSpec(w.func, arg=arg, offset=offset,
                                 out_dtype=w.dtype))
            fps.append((w.func, repr(r), offset, w.dtype))
            out_dicts.append(arg.out_dict)
        else:
            specs.append(WinSpec(w.func, out_dtype=w.dtype))
            fps.append((w.func,))
            out_dicts.append(None)
        out_bounds.append(None)
    fp = (tuple(repr(e) for e in pres), tuple(repr(e) for e in ores),
          tuple(plan.ascending), tuple(plan.nulls_first), tuple(fps))
    return fp, part_keys, order_keys, specs, out_dicts, out_bounds


def _seg_scan(op, vals, start):
    """Segmented inclusive scan: restart `op` at every True in `start`."""
    def combine(a, b):
        af, av = a
        bf, bv = b
        return af | bf, jnp.where(bf, bv, op(av, bv))
    _, out = jax.lax.associative_scan(combine, (start, vals))
    return out


def window_batch(batch: DeviceBatch, part_keys: list[Compiled],
                 order_keys: list[Compiled], ascending: list[bool],
                 nulls_first: list[bool], specs: list[WinSpec],
                 out_schema: T.Schema, consts: tuple = ()) -> DeviceBatch:
    """Jit-traceable: input batch -> input columns + one column per spec.
    Output rows keep the ORIGINAL lane positions (and the original live
    mask); only the appended values are computed in window order."""
    env = Env.from_batch(batch, consts)
    cap = batch.capacity
    live = batch.live

    part_lanes: list = []
    part_nulls: list = []
    sort_lanes: list = []
    for k in part_keys:
        v, nl = k.fn(env)
        for lane in K.group_lanes_for(v, k.dtype.is_float):
            part_lanes.append(lane)
            part_nulls.append(nl)
        sort_lanes.extend(K.sort_lanes_for(v, nl, k.dtype.is_float, True,
                                           False))
    order_lanes: list = []
    order_nulls: list = []
    for k, a, nf in zip(order_keys, ascending, nulls_first):
        v, nl = k.fn(env)
        for lane in K.group_lanes_for(v, k.dtype.is_float):
            order_lanes.append(lane)
            order_nulls.append(nl)
        sort_lanes.extend(K.sort_lanes_for(v, nl, k.dtype.is_float, a, nf))

    perm = K.lex_argsort(sort_lanes, live)
    s_live = jnp.take(live, perm)
    pos = jnp.arange(cap, dtype=jnp.int32)

    def changed(lanes, nulls):
        """True where the sorted row differs from its predecessor on any
        lane (null-aware); row 0 always True."""
        flag = pos == 0
        for lane, nl in zip(lanes, nulls):
            sv = jnp.take(lane, perm)
            prev = jnp.concatenate([sv[:1], sv[:-1]])
            diff = sv != prev
            if nl is not None:
                sn = jnp.take(nl, perm)
                pn = jnp.concatenate([sn[:1], sn[:-1]])
                diff = diff | (sn != pn)
            flag = flag | diff
        return flag

    if part_lanes:
        seg_start = changed(part_lanes, part_nulls)
    else:
        seg_start = pos == 0
    # dead rows sort last; give each its own segment so nothing leaks
    seg_start = seg_start | ~s_live
    peer_start = seg_start | (changed(order_lanes, order_nulls)
                              if order_lanes else jnp.zeros((cap,), bool))

    seg_start_pos = jax.lax.associative_scan(
        jnp.maximum, jnp.where(seg_start, pos, 0))
    peer_start_pos = jax.lax.associative_scan(
        jnp.maximum, jnp.where(peer_start, pos, 0))
    peer_end_pos = _end_positions(peer_start, pos, cap)
    seg_end_pos = _end_positions(seg_start, pos, cap)

    out_cols = list(batch.columns)
    inv = jnp.zeros((cap,), jnp.int32).at[perm].set(pos)

    def unsort(vals, nulls=None):
        v = jnp.take(vals, inv)
        n = jnp.take(nulls, inv) if nulls is not None else None
        return v, n

    for spec, f in zip(specs, out_schema.fields[len(batch.columns):]):
        if spec.kind == "row_number":
            win = (pos - seg_start_pos + 1).astype(jnp.int64)
            v, n = unsort(win)
        elif spec.kind == "rank":
            win = (peer_start_pos - seg_start_pos + 1).astype(jnp.int64)
            v, n = unsort(win)
        elif spec.kind == "dense_rank":
            cnp = jnp.cumsum(peer_start.astype(jnp.int64))
            win = cnp - jnp.take(cnp, seg_start_pos) + 1
            v, n = unsort(win)
        elif spec.kind in ("lag", "lead"):
            av, an = spec.arg.fn(env)
            sv = jnp.take(av, perm)
            sn = jnp.take(an, perm) if an is not None else None
            off = spec.offset if spec.kind == "lag" else -spec.offset
            src = pos - off
            valid = (src >= seg_start_pos) & (src <= seg_end_pos) & s_live
            safe = jnp.clip(src, 0, cap - 1)
            win = jnp.take(sv, safe)
            wn = ~valid
            if sn is not None:
                wn = wn | jnp.take(sn, safe)
            v, n = unsort(win, wn)
        else:  # aggregate over the window
            v, n = _window_agg(spec, env, perm, s_live, seg_start_pos,
                               seg_end_pos, peer_end_pos,
                               bool(order_lanes), cap)
            v, n = unsort(v, n)
        want = f.dtype.device_dtype()
        if v.dtype != want:
            v = v.astype(want)
        out_cols.append(DeviceColumn(f.dtype, v, n, None))
    return DeviceBatch(out_schema, out_cols, live)


def _end_positions(start_flags, pos, cap):
    """Last position of each row's run, given run-start flags: the NEXT
    start position scanned from the right, minus one."""
    import jax as _jax
    nxt = jnp.concatenate([
        jnp.where(start_flags[1:], pos[1:], cap).astype(jnp.int32),
        jnp.full((1,), cap, jnp.int32)])
    return _jax.lax.associative_scan(jnp.minimum, nxt, reverse=True) - 1


def _window_agg(spec: WinSpec, env: Env, perm, s_live, seg_start_pos,
                seg_end_pos, peer_end_pos, has_order: bool, cap):
    """SUM/COUNT/AVG/MIN/MAX over the frame. With ORDER BY: running value at
    the row's peer-group END (RANGE default frame); else whole partition
    (= value at the segment's last row, broadcast via the running scan at
    segment end)."""
    func = spec.agg_func
    if spec.arg is not None:
        av, an = spec.arg.fn(env)
        sv = jnp.take(av, perm)
        valid = s_live if an is None else (s_live & ~jnp.take(an, perm))
    else:  # COUNT(*)
        sv = jnp.ones((cap,), jnp.int64)
        valid = s_live

    at = peer_end_pos if has_order else seg_end_pos

    if func in (AggFunc.SUM, AggFunc.AVG, AggFunc.COUNT,
                AggFunc.COUNT_STAR):
        acc = jnp.float64 if (func is AggFunc.AVG or
                              (func is AggFunc.SUM and
                               spec.out_dtype.is_float)) else jnp.int64
        vals = jnp.where(valid, sv.astype(acc), jnp.zeros((), acc))
        cnt1 = valid.astype(jnp.int64)
        cs = jnp.cumsum(vals)
        cc = jnp.cumsum(cnt1)
        before_v = jnp.where(seg_start_pos > 0,
                             jnp.take(cs, jnp.clip(seg_start_pos - 1, 0,
                                                   None)),
                             jnp.zeros((), acc))
        before_c = jnp.where(seg_start_pos > 0,
                             jnp.take(cc, jnp.clip(seg_start_pos - 1, 0,
                                                   None)),
                             jnp.int64(0))
        total = jnp.take(cs, at) - before_v
        count = jnp.take(cc, at) - before_c
        if func in (AggFunc.COUNT, AggFunc.COUNT_STAR):
            return count, None
        if func is AggFunc.AVG:
            denom = jnp.where(count == 0, 1, count).astype(jnp.float64)
            return total / denom, count == 0
        return total, count == 0
    # MIN / MAX: segmented running scan on a sentinel-masked lane, read at
    # the frame end, then exact value via the winning-lane trick is overkill
    # here — integers/floats compare directly (strings go through rank ids
    # upstream; not supported as window agg args yet)
    if spec.arg is not None and spec.arg.dtype.is_float:
        lane = sv.astype(jnp.float64)
        ident = jnp.asarray(jnp.inf if func is AggFunc.MIN else -jnp.inf,
                            jnp.float64)
    else:
        lane = sv.astype(jnp.int64)
        ident = jnp.asarray(jnp.iinfo(jnp.int64).max if func is AggFunc.MIN
                            else jnp.iinfo(jnp.int64).min, jnp.int64)
    masked = jnp.where(valid, lane, ident)
    op = jnp.minimum if func is AggFunc.MIN else jnp.maximum
    seg_start = jnp.arange(cap, dtype=jnp.int32) == seg_start_pos
    run = _seg_scan(op, masked, seg_start)
    cnt = _seg_scan(jnp.add, valid.astype(jnp.int64), seg_start)
    out = jnp.take(run, at)
    none = jnp.take(cnt, at) == 0
    return out, none
