"""Canonical shape families: the engine-wide capacity policy.

Every static-shaped buffer in the engine (scan batches, intermediate
compactions, aggregate output segments, exchange buckets, direct-join
positional tables) is padded to a *canonical capacity* so that XLA programs
are keyed by a SMALL family of shapes instead of one shape per cardinality.
BENCH_r05 measured 12-31 s cold compiles per query against 0.08-1.2 s warm —
for ad-hoc traffic, compilation IS the latency, so the shape family is sized
for program reuse first and padding waste second:

- **small band** (n <= 2^16): exact power-of-two buckets. Programs here
  compile in well under a second, and tight padding matters more than
  sharing (an 8-row dimension table must not become a 32-row one).
- **coarse band** (2^16 < n <= 2^22): members every OTHER power of two
  (2^18, 2^20, 2^22 — geometric ratio 4). This is the ad-hoc sweet spot:
  a query shape at scale factor s and at 2s quantizes to the SAME member,
  so e.g. TPC-H q3 at SF0.02 and SF0.04 lower to one XLA program. Padding
  cost is bounded 4x on buffers of at most 32 MB/lane-column.
- **large band** (n > 2^22): power-of-two again. At HBM scale a 4x pad is
  an OOM, not a tax — and the out-of-core tiers (GRACE/chunked) already
  pin their partition capacities to shared program shapes.

**Hysteresis.** Above the small band, the row count is padded by 1/32
(~3%) before quantizing: a cardinality sitting just under a family boundary
rounds UP, so day-to-day drift across the boundary (inserts, scale-factor
nudges) cannot flip-flop a table between two members and double-compile
every downstream program.

`IGLOO_TPU_SHAPE_FAMILY=pow2` restores plain power-of-two bucketing
everywhere (A/B knob; `coarse` — or unset — selects the family above).

`exec/batch.round_capacity` delegates here, so every existing call site
(scans, compacts, match capacities, segment counts, shuffle buckets)
inherits the policy without local changes.
"""
from __future__ import annotations

import os

MIN_CAPACITY = 8

# upper edge of the exact-pow2 small band
COARSE_FLOOR = 1 << 16
# coarse members every STEP powers of two up to COARSE_CEIL, pow2 above
COARSE_STEP = 2
COARSE_CEIL = 1 << 22

# hysteresis headroom above the small band: n is padded by n >> 3%-ish
# (1/32) before quantizing, so near-boundary cardinalities round up once
# instead of flip-flopping across the boundary as data drifts
_HEADROOM_SHIFT = 5


def family_mode() -> str:
    """'coarse' (default) or 'pow2' (IGLOO_TPU_SHAPE_FAMILY knob)."""
    raw = os.environ.get("IGLOO_TPU_SHAPE_FAMILY", "coarse").strip().lower()
    return "pow2" if raw == "pow2" else "coarse"


def _pow2(n: int) -> int:
    c = MIN_CAPACITY
    while c < n:
        c <<= 1
    return c


def _is_member(n: int) -> bool:
    """True when n is already a family member (coarse mode)."""
    if n < MIN_CAPACITY or n & (n - 1):
        return False
    if n <= COARSE_FLOOR or n > COARSE_CEIL:
        return True
    # coarse band: every COARSE_STEP-th power of two above the floor
    return (n.bit_length() - COARSE_FLOOR.bit_length()) % COARSE_STEP == 0


def canonical_capacity(n: int) -> int:
    """Smallest family member >= n (with hysteresis headroom above the
    small band). This is THE quantization every padded lane goes through.
    IDEMPOTENT: a value that is already a member maps to itself — call
    sites routinely re-round existing capacities (spec_cap, GRACE partition
    caps), and headroom there would inflate a full family step per pass."""
    if n <= COARSE_FLOOR or family_mode() == "pow2":
        return _pow2(n)
    if _is_member(n):
        return n
    n_eff = n + (n >> _HEADROOM_SHIFT)
    if n_eff > COARSE_CEIL:
        return _pow2(n_eff)
    c = COARSE_FLOOR
    step = COARSE_STEP
    while c < n_eff:
        c <<= step
    return c


def capacity_family(limit: int) -> list:
    """The family members up to `limit` (docs/tests; not a hot path).
    Mirrors canonical_capacity: pow2 through COARSE_FLOOR, then
    COARSE_FLOOR << 2k coarse members through COARSE_CEIL, pow2 above."""
    out = []
    c = MIN_CAPACITY
    while c <= min(limit, COARSE_FLOOR):
        out.append(c)
        c <<= 1
    if family_mode() == "pow2":
        while c <= limit:
            out.append(c)
            c <<= 1
        return out
    c = COARSE_FLOOR << COARSE_STEP
    while c <= min(limit, COARSE_CEIL):
        out.append(c)
        c <<= COARSE_STEP
    c = COARSE_CEIL << 1
    while c <= limit:
        out.append(c)
        c <<= 1
    return out


def pow2_block(n: int, cap: int) -> int:
    """Largest power of two <= min(n, cap) (>= 1). The Pallas kernels
    (exec/dispatch.py) derive their grid block sizes through this: every
    canonical capacity is a power of two, so blocks chosen here always
    divide the padded lane count exactly and kernel programs stay keyed by
    the same small shape family as the rest of the engine."""
    b = 1
    while b * 2 <= min(n, cap):
        b <<= 1
    return b


def tuning_capacities(limit: int = COARSE_FLOOR) -> list:
    """Representative family members for offline kernel sweeps
    (exec/autotune.py, scripts/autotune_sweep.py): every member from a
    quarter of the small-band ceiling up to `limit` — the shapes real
    operand sets quantize to. Smaller capacities are skipped on purpose:
    kernels there finish too fast for block/window choice to matter, and
    every swept capacity costs a full candidate-grid benchmark."""
    return [c for c in capacity_family(limit) if c >= COARSE_FLOOR // 4]


def canonical_direct_table(lo: int, hi: int) -> tuple:
    """Canonical (base, table_size) for a direct-join positional table over
    key bounds [lo, hi]. The raw bounds are data-dependent constants; baking
    them into a compiled program (and its cache key) would give every scale
    factor its own join program. Instead the table size is quantized to the
    capacity family (with a 4/3 margin so the base can grid-align) and the
    base is floor-aligned to a quarter-table grid: nearby bounds — e.g. TPC-H
    orderkey ranges at neighboring scale factors — share one (base, size)
    pair and therefore one compiled join. Guarantees base <= lo and
    base + table_size > hi, so every key in [lo, hi] still lands in-table;
    the extra slots stay empty (-1) and can never match a probe."""
    rng = int(hi) - int(lo) + 1
    tcap = canonical_capacity((rng * 4 + 2) // 3)
    grid = max(tcap // 4, 1)
    base = (int(lo) // grid) * grid
    return base, tcap
