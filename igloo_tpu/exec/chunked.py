"""Partition-at-a-time (chunked) execution for tables larger than one
DeviceBatch budget.

Reuses the cluster tier's fragmenting planner (cluster/fragment.py) with every
fragment executed IN-PROCESS: scans stride the provider's partitions
(parquet row groups, CSV files, MemTable splits), decomposable aggregates
become per-chunk partial aggregates merged by a final fragment, and
intermediate results live as host Arrow tables (partials are group-count
sized, not input sized). The device never materializes more than one chunk of
the base table at a time.

Ceiling (documented per the build plan): only decomposable-aggregate-over-scan
pipelines (Q1/Q6 shape) actually stream chunk-at-a-time — `chunk_count` routes
ONLY those here. Plans whose over-budget scan feeds anything else (a bare
sort/limit, a join side, a DISTINCT aggregate) would union all chunks back into
one device batch, so they take the normal path unchanged; over-budget JOIN
trees route through the partitioned GRACE tier instead (exec/grace.py — see
docs/out_of_core.md for the full fallback ladder).

Reference analog: the 1024-row streaming read batches of
crates/engine/src/operators/parquet_scan.rs:54, which flow through operators
one channel at a time but are never exploited for memory-bounded aggregation.
"""
from __future__ import annotations

from typing import Optional

import pyarrow as pa

from igloo_tpu.plan import logical as L
from igloo_tpu.utils import stats, tracing


def estimated_bytes(provider) -> Optional[int]:
    """Best-effort source size, host-side, without reading data."""
    est = getattr(provider, "estimated_bytes", None)
    if est is not None:
        try:
            return est()
        except Exception:
            return None
    return None


def estimated_lane_bytes(provider) -> Optional[int]:
    """Estimated size once RESIDENT as device lanes: the raw estimate times
    the provider's `bytes_expansion` (compressed parquet decodes to ~3-4x
    its file size as int64/float64 lanes; in-memory Arrow tables report
    decoded bytes already, factor 1), times the measured carrier ratio
    (codec.carrier_ratio — columns stay NARROW in HBM since PR 16, so a
    provider whose scans ride int8/int16 carriers prices well under its
    wide-lane size; unmeasured providers price at ratio 1.0, the safe
    upper bound). Every device-memory budget check — chunked tier, GRACE
    trigger, serving's predict_hbm_bytes — flows through THIS, not file
    bytes."""
    nb = estimated_bytes(provider)
    if nb is None:
        return None
    from igloo_tpu.exec import codec
    return int(nb * getattr(provider, "bytes_expansion", 1.0)
               * codec.carrier_ratio(provider))


def chunk_count(plan: L.LogicalPlan, budget_bytes: int) -> int:
    """How many chunks the largest over-budget scanned table needs (0 = no
    chunking). Only scans that the fragment planner can actually stream —
    i.e. feeding a DECOMPOSABLE aggregate through scan/filter/project nodes —
    count: chunking anything else just unions the chunks back into one batch
    and pays fragment overhead for no memory bound (see module docstring)."""
    from igloo_tpu.cluster.fragment import _DECOMPOSABLE, _is_local
    want = 0
    for node in L.walk_plan(plan):
        if not (isinstance(node, L.Aggregate) and _is_local(node.input) and
                not any(a.distinct for a in node.aggs) and
                all(a.func in _DECOMPOSABLE for a in node.aggs)):
            continue
        for sc in L.walk_plan(node.input):
            if isinstance(sc, L.Scan) and sc.provider is not None and \
                    sc.partition is None:
                nbytes = estimated_lane_bytes(sc.provider)
                try:
                    parts = sc.provider.num_partitions()
                except Exception:
                    parts = 1
                if nbytes is not None and nbytes > budget_bytes and parts > 1:
                    # the chunk count is DERIVED from the budget (how many
                    # budget-sized pieces the table decodes into); the only
                    # clamp left is the provider's own partition granularity,
                    # and hitting it means per-chunk memory exceeds the
                    # budget — warn instead of silently un-bounding (the old
                    # hard min(.., 64) did exactly that past 64x budgets)
                    need = -(-nbytes // max(budget_bytes, 1))
                    if need > parts:
                        tracing.counter("chunked.chunks_clamped")
                        tracing.log.warning(
                            "chunked: %d chunks needed to bound memory but "
                            "provider has only %d partitions; per-chunk "
                            "working set will exceed the %d-byte budget",
                            need, parts, budget_bytes)
                    want = max(want, min(parts, need))
    return want


class LocalChunkExecutor:
    """Executes a fragmented plan in-process, one fragment at a time."""

    def __init__(self, catalog, jit_cache: Optional[dict] = None,
                 use_jit: bool = True, batch_cache=None, chunks: int = 4):
        self.catalog = catalog
        self._jit_cache = jit_cache
        self._use_jit = use_jit
        self._batch_cache = batch_cache
        self.chunks = max(2, chunks)

    def execute_to_arrow(self, plan: L.LogicalPlan) -> pa.Table:
        from igloo_tpu.catalog import MemTable
        from igloo_tpu.cluster import serde
        from igloo_tpu.cluster.fragment import FRAG_PREFIX, DistributedPlanner
        from igloo_tpu.exec.executor import Executor

        planner = DistributedPlanner(
            [f"__chunk{i}" for i in range(self.chunks)])
        # chunk slots are not workers: Exchange-rooted shuffle fragments
        # need the worker fragment store + bucket fetch protocol, which the
        # in-process Executor below does not speak — plain partitioned scan
        # fragments only
        planner.shuffle_enabled = False
        frags = planner.plan(plan)

        results: dict[str, pa.Table] = {}
        base = self.catalog

        class _Overlay:
            def get(self, name: str):
                key = name.lower()
                if key.startswith(FRAG_PREFIX):
                    return MemTable(results[key[len(FRAG_PREFIX):]])
                return base.get(name)

        overlay = _Overlay()
        # deserialize what we can upfront (fragments referencing earlier
        # results resolve later) and enqueue every partitioned scan read, in
        # fragment order, on the storage prefetcher: the reader thread
        # decodes chunk k+1's row groups while chunk k computes on device
        # (docs/storage.md#prefetch; IGLOO_STORAGE_PREFETCH=0 kills it)
        from igloo_tpu.storage import prefetch as _prefetch
        plans: dict[str, L.LogicalPlan] = {}
        items: list[tuple] = []
        for f in frags:
            try:
                p = serde.plan_from_json(f.plan, overlay)
            except Exception:
                continue  # needs a not-yet-computed fragment result
            plans[f.id] = p
            for sc in L.walk_plan(p):
                if isinstance(sc, L.Scan) and sc.provider is not None \
                        and sc.partition:
                    items.extend((sc.provider, i, sc.projection,
                                  sc.pushed_filters) for i in sc.partition)
        # fragments are appended children-first, so sequential order is
        # dependency-safe; chunk results are host Arrow (partials are small)
        with _prefetch.scan_prefetch(items), \
                stats.op("ChunkedExecution", chunks=self.chunks,
                         fragments=len(frags)):
            for i, f in enumerate(frags):
                p = plans.get(f.id)
                if p is None:
                    p = serde.plan_from_json(f.plan, overlay)
                ex = Executor(self._jit_cache, use_jit=self._use_jit,
                              batch_cache=self._batch_cache)
                with stats.op(f"Chunk[{i}]" if i < len(frags) - 1
                              else "ChunkMerge"):
                    results[f.id] = ex.execute_to_arrow(p)
                    # host Arrow row count — free, no device sync
                    stats.set_rows(results[f.id].num_rows)
            out = results[frags[-1].id]
            stats.set_rows(out.num_rows)
        return out
