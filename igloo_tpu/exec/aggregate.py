"""Group-by / aggregate kernel.

The reference has NO aggregation in its custom engine (DataFusion handles it on the
working path; the custom physical planner lowers only scan/filter/project/join,
physical_planner.rs:23-140). This is the TPU design from SURVEY.md §7 step 4:
sort-based segment reduction — one fused XLA computation, static shapes:

    keys -> lexicographic stable argsort -> contiguous groups -> boundary flags
         -> segment_sum/min/max over static segment count (= capacity)

Output capacity equals input capacity; row `i` of the output is group `i`
(compacted to the front, `live` marks real groups). No hashing: grouping equality
is exact lane comparison after the sort, so no collision handling is needed.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from igloo_tpu import types as T
from igloo_tpu.exec import dispatch
from igloo_tpu.exec import kernels as K
from igloo_tpu.exec.batch import DeviceBatch, DeviceColumn, DictInfo
from igloo_tpu.exec.dispatch import DIRECT_SEG_SMALL_LIMIT
from igloo_tpu.exec.expr_compile import Compiled, Env
from igloo_tpu.plan.expr import AggFunc
from igloo_tpu.utils import tracing


@dataclass(frozen=True)
class AggSpec:
    func: AggFunc
    arg: Optional[Compiled]       # None only for COUNT_STAR
    out_dtype: T.DataType
    out_dict: Optional[DictInfo]  # MIN/MAX over strings keep the arg dictionary
    # MIN/MAX over an UNSORTED (high-cardinality) string dictionary: ids are
    # not ranks, so comparisons run on this rank lane while values/output stay
    # ids (executor wires expr_compile.rank_lane here)
    order_arg: Optional[Compiled] = None


def minmax_order_arg(func: AggFunc, arg: Optional[Compiled],
                     comp) -> Optional[Compiled]:
    """Rank lane for MIN/MAX over an unsorted high-cardinality string
    dictionary (see AggSpec.order_arg); None when ids already order correctly."""
    if func not in (AggFunc.MIN, AggFunc.MAX) or arg is None or \
            arg.out_dict is None or arg.out_dict.is_sorted:
        return None
    from igloo_tpu.exec.expr_compile import rank_lane
    return rank_lane(arg, comp)


_DENSE_INT_SEG_LIMIT = 1 << 23


def seg_dims_for(groups: list[Compiled],
                 n_aggs: Optional[int] = None,
                 input_capacity: Optional[int] = None) -> Optional[tuple]:
    """If every group key is directly indexable — a dictionary-encoded string
    (ids in [0, len)), a boolean, or (round 5) an integer-family column with
    host-known dense bounds — return per-key (bucket count, offset) pairs
    (+1 bucket for NULL). The aggregate then scatters straight into
    `prod(dims)` segments instead of lex-sorting every input lane (the sort
    is O(n log n) over the FULL batch capacity; Q1 groups 8M lanes into 6
    buckets, and q18's sum-per-orderkey groups 8M lanes into 6M dense-int
    segments — 1 scatter instead of a multi-lane sort).

    Large dense-int segment spaces (> 2^16) are only worth one scatter per
    aggregate, so they require `n_aggs` (callers that cannot bound the
    scatter count — the sharded partial path — omit it and keep the small
    limit). Host-side decision: callers must fold the result into their jit
    cache key."""
    dims = []
    for g in groups:
        if g.dtype is T.BOOL:
            dims.append((3, 0))
        elif g.dtype.is_string and g.out_dict is not None:
            dims.append((len(g.out_dict.values) + 1, 0))
        elif (g.dtype.is_integer or g.dtype.is_temporal) and \
                g.out_bounds is not None:
            lo, hi = g.out_bounds
            dims.append((int(hi) - int(lo) + 2, int(lo)))
        else:
            return None
    prod = 1
    for d, _off in dims:
        prod *= d
    if not dims or prod <= 0:
        return None
    if prod > DIRECT_SEG_SMALL_LIMIT:
        # the big-segment branch trades one ~1s scatter per aggregate value
        # for the multi-lane sort: only worth it when the scatter count is
        # small (AVG = sum+count = 2 scatters) AND the segment space does not
        # dwarf the batch (bounds are GLOBAL scan stats — a filtered 64K-lane
        # batch grouping by a 6M-wide key must keep the sort path, not
        # allocate 8M-segment outputs). The threshold is shared with the
        # Pallas dispatch layer's hash-agg table bound (exec/dispatch.py)
        # so the two eligibility checks cannot drift.
        if n_aggs is None or n_aggs > 2 or prod > _DENSE_INT_SEG_LIMIT:
            return None
        if input_capacity is None or prod > 2 * input_capacity:
            return None
    tracing.counter("agg.direct_scatter")
    return tuple(dims)


def aggregate_batch(batch: DeviceBatch, groups: list[Compiled],
                    aggs: list[AggSpec], out_schema: T.Schema,
                    consts: tuple = (),
                    seg_dims: Optional[tuple] = None,
                    pack_spec: Optional[tuple] = None,
                    pallas_agg: Optional[tuple] = None):
    # seg_dims entries are (bucket_count, value_offset) pairs — see
    # seg_dims_for
    """Pure, jit-traceable: DeviceBatch -> DeviceBatch of one row per group.
    Output columns carry no dictionaries — the executor re-attaches them.
    `seg_dims` (from seg_dims_for, included in the caller's cache key) selects
    the direct-scatter fast path; output capacity is then the padded segment
    count, not the input capacity. `pack_spec` (kernels.plan_group_packing,
    also part of the caller's cache key) is (spec, packed key indices): the
    indexed keys fuse into ONE int lane, collapsing their share of the
    multi-lane lex_argsort chain to a single sort pass — when every key packs
    (all-integer group-bys) the whole chain becomes one argsort, and a
    q18-shaped 5-key group-by with one float key sorts 3 lanes instead of
    10+. `pallas_agg` (dispatch.plan_segagg, also a cache-key part; requires
    a full-cover pack_spec) replaces the sort entirely with the one-pass
    Pallas hash aggregation — the return value is then (DeviceBatch,
    overflow flag) instead of a bare DeviceBatch."""
    env = Env.from_batch(batch, consts)
    cap = batch.capacity
    live = batch.live

    # evaluate group keys once
    gvals: list[jax.Array] = []
    gnulls: list[Optional[jax.Array]] = []
    for g in groups:
        v, nl = g.fn(env)
        gvals.append(v)
        gnulls.append(nl)

    if not groups:
        return _global_aggregate(env, aggs, out_schema, live)

    if seg_dims is not None and len(seg_dims) == len(groups):
        return _direct_aggregate(env, groups, gvals, gnulls, aggs, out_schema,
                                 live, seg_dims)

    if pallas_agg is not None and pack_spec is not None and \
            len(pack_spec[1]) == len(groups):
        return _pallas_hash_aggregate(env, groups, gvals, gnulls, aggs,
                                      out_schema, live, pack_spec,
                                      pallas_agg, consts)

    # sort path. With a pack_spec, the indexed keys fuse into ONE packed lane
    # (NULL is a digit, so no separate null lanes for them). Grouping never
    # cares about lane SIGNIFICANCE order — only equal-key adjacency — so any
    # unpacked keys' null/NaN flags AND the live bit fold into the packed
    # lane's spare high bits when they fit: a q18-shaped group-by (4 packable
    # keys + 1 float) then sorts TWO lanes (float value, folded packed)
    # instead of the 11-pass lex chain; an all-packed group-by sorts ONE.
    packed = None
    packed_idx: tuple = ()
    rest: list = []
    if pack_spec is not None:
        spec, packed_idx = pack_spec
        packed = K.pack_key_lane(spec, [gvals[i] for i in packed_idx],
                                 [gnulls[i] for i in packed_idx], consts)
        rest = [i for i in range(len(groups)) if i not in packed_idx]
        pack_bits = sum(card.bit_length() - 1 for card, _, _ in spec[2])
        n_flags = 1 + sum((1 if groups[i].dtype.is_float else 0) +
                          (1 if gnulls[i] is not None else 0) for i in rest)
    if packed is not None and not rest:
        # every key packed: one argsort (dead rows via the packed sentinel)
        perm = jnp.argsort(K.packed_sort_key(packed, live), stable=True)
        s_lanes, s_nulls = [jnp.take(packed, perm)], [None]
    elif packed is not None and pack_bits + n_flags <= 63:
        # folded mixed path: value lanes (null-masked; floats NaN-normalized)
        # sort first, the folded lane [dead | flags | packed digits] sorts
        # last — its dead bit replaces lex_argsort's trailing live pass
        lane = packed.astype(jnp.int64)
        shift = pack_bits
        value_lanes: list = []
        for i in rest:
            v, nl, g = gvals[i], gnulls[i], groups[i]
            if nl is not None:
                # mask BEFORE deriving the NaN flag: this branch compares raw
                # lanes with no null awareness (s_nulls is all-None), so
                # under-null storage — which may be NaN on one row and finite
                # on another — must collapse to one canonical value or the
                # NULL group would split
                v = jnp.where(nl, jnp.zeros((), v.dtype), v)
            if g.dtype.is_float:
                vnorm, nan = K.normalize_float(v)
                lane = lane + (nan.astype(jnp.int64) << shift)
                shift += 1
                v = vnorm
            if nl is not None:
                lane = lane + (nl.astype(jnp.int64) << shift)
                shift += 1
            value_lanes.append(v)
        lane = lane + ((~live).astype(jnp.int64) << shift)
        shift += 1
        if shift <= 31:
            lane = lane.astype(jnp.int32)
        perm = jnp.arange(cap, dtype=jnp.int32)
        for v in reversed(value_lanes):
            perm = jnp.take(perm,
                            jnp.argsort(jnp.take(v, perm), stable=True))
        perm = jnp.take(perm,
                        jnp.argsort(jnp.take(lane, perm), stable=True))
        s_lanes = [jnp.take(lane, perm)] + \
            [jnp.take(v, perm) for v in value_lanes]
        s_nulls = [None] * len(s_lanes)
    else:
        # lex chain over the unpacked keys — equality lanes (string ids are
        # already ranks; floats decompose into nan-flag + normalized-value
        # lanes, no 64-bit bitcasts, TPU-safe) — led by the packed lane when
        # one exists (subset pack whose fold flags overflowed the spare bits)
        flat_lanes: list = [packed] if packed is not None else []
        flat_nulls: list = [None] if packed is not None else []
        sort_lanes: list = [(packed, True)] if packed is not None else []
        for i, (v, nl, g) in enumerate(zip(gvals, gnulls, groups)):
            if i in packed_idx:
                continue
            for eq in K.group_lanes_for(v, g.dtype.is_float):
                flat_lanes.append(eq)
                flat_nulls.append(nl)
            sort_lanes.extend(K.sort_lanes_for(v, nl, g.dtype.is_float,
                                               True, False))
        perm = K.lex_argsort(sort_lanes, live)
        s_lanes = [jnp.take(l, perm) for l in flat_lanes]
        s_nulls = [jnp.take(nl, perm) if nl is not None else None
                   for nl in flat_nulls]
    s_live = jnp.take(live, perm)
    seg, start = K.group_segments(s_lanes, s_nulls, s_live)
    num_groups = jnp.sum(start.astype(jnp.int32))

    # sorted segments are CONTIGUOUS runs, so segment boundaries come from the
    # start flags (no scatter): row k of the output is segment k, whose first
    # sorted position is the k-th True in `start` — compact_perm lists those
    # positions ascending. bounds = (start_idx, end_idx) per output row.
    start_idx = K.compact_perm(start)  # [cap] int32; rows >= num_groups garbage
    nxt = jnp.concatenate([start_idx[1:], jnp.full((1,), cap, jnp.int32)])
    k_idx = jnp.arange(cap, dtype=jnp.int32)
    end_idx = jnp.where(k_idx + 1 < num_groups, nxt, jnp.int32(cap)) - 1
    end_idx = jnp.clip(end_idx, 0, cap - 1)
    bounds = (start_idx, end_idx)
    first_pos = start_idx

    out_cols: list[DeviceColumn] = []
    # group key output columns
    for v, nl, g in zip(gvals, gnulls, groups):
        sv = jnp.take(jnp.take(v, perm), first_pos)
        snl = jnp.take(jnp.take(nl, perm), first_pos) if nl is not None else None
        # out_dict here is trace-time metadata: correct for eager (direct) use;
        # under the executor's jit cache it may be stale on a cache hit, so the
        # executor re-attaches current dictionaries after every call
        out_cols.append(DeviceColumn(g.dtype, sv.astype(g.dtype.device_dtype())
                                     if sv.dtype != g.dtype.device_dtype() else sv,
                                     snl, g.out_dict))

    # aggregates via segment reductions over sorted order
    for spec in aggs:
        out_cols.append(_reduce_one(spec, env, perm, seg, s_live, cap, cap,
                                    bounds=bounds))

    out_live = jnp.arange(cap, dtype=jnp.int32) < num_groups
    return DeviceBatch(out_schema, out_cols, out_live)


def _run_sum(vals: jax.Array, bounds) -> jax.Array:
    """Per-segment sum over CONTIGUOUS (sorted) segments as cumsum boundary
    differences — gathers only, no scatter (a TPU scatter over a full lane
    costs ~300ms; this is one bandwidth-bound pass + two gathers).

    INTEGER lanes only: int cumsum differences are exact (wraparound cancels),
    while a float cumsum would (a) let one group's inf/NaN poison every LATER
    group (inf - inf = NaN at the boundary difference) and (b) round each
    group at the magnitude of the global running sum instead of its own.
    Float sums keep the isolated segment reduction."""
    start_idx, end_idx = bounds
    cs = jnp.cumsum(vals)
    before = jnp.where(start_idx > 0,
                       jnp.take(cs, jnp.clip(start_idx - 1, 0, None)),
                       jnp.zeros((), cs.dtype))
    return jnp.take(cs, end_idx) - before


def _reduce_one(spec: AggSpec, env: Env, perm, seg, s_live, cap,
                nseg, bounds=None) -> DeviceColumn:
    """Segment reduction for one aggregate. `perm` sorts rows into segment
    order (None = rows already aligned with `seg`); output arrays have length
    `nseg` (= cap on the sort path, the padded segment count on the direct
    path). `bounds` = per-output-row (start, end) sorted positions when
    segments are contiguous: INTEGER sums (counts, int SUM) then run
    scatter-free via cumsum differences (see _run_sum for why floats don't)."""
    def ssum(vals):
        if bounds is not None and jnp.issubdtype(vals.dtype, jnp.integer):
            return _run_sum(vals, bounds)
        return K.seg_sum(vals, seg, nseg)

    if spec.func is AggFunc.COUNT_STAR:
        cnt = ssum(s_live.astype(jnp.int64))
        return DeviceColumn(T.INT64, cnt, None, None)

    v, nl = spec.arg.fn(env)
    sv = v if perm is None else jnp.take(v, perm)
    snl = nl if perm is None else (jnp.take(nl, perm)
                                   if nl is not None else None)
    valid = s_live if snl is None else (s_live & ~snl)
    n_valid = ssum(valid.astype(jnp.int64))
    all_null = n_valid == 0

    if spec.func is AggFunc.COUNT:
        return DeviceColumn(T.INT64, n_valid, None, None)

    if spec.func is AggFunc.SUM or spec.func is AggFunc.AVG:
        acc_dtype = jnp.float64 if (spec.out_dtype.is_float or
                                    spec.func is AggFunc.AVG) else jnp.int64
        sval = jnp.where(valid, sv.astype(acc_dtype), jnp.zeros((), acc_dtype))
        total = ssum(sval)
        if spec.func is AggFunc.AVG:
            denom = jnp.where(all_null, 1, n_valid).astype(jnp.float64)
            return DeviceColumn(T.FLOAT64, total / denom, all_null, None)
        return DeviceColumn(spec.out_dtype,
                            total.astype(spec.out_dtype.device_dtype()),
                            all_null, None)

    # MIN / MAX: sentinel-masked segment reduce on a comparable lane, then an
    # exact gather of the original value at a winning position (so e.g. a NaN
    # winner comes back as NaN, not as its +inf ordering surrogate)
    pos = jnp.arange(cap, dtype=jnp.int32)
    cmp_src = sv
    if spec.order_arg is not None:
        ov, _ = spec.order_arg.fn(env)
        cmp_src = ov if perm is None else jnp.take(ov, perm)
    if spec.arg.dtype.is_float:
        vnorm, nan = K.normalize_float(cmp_src)
        lane = jnp.where(nan, jnp.asarray(jnp.inf, vnorm.dtype), vnorm)
        lo = jnp.asarray(-jnp.inf, lane.dtype)
        hi = jnp.asarray(jnp.inf, lane.dtype)
    else:
        lane = cmp_src.astype(jnp.int64)
        lo = jnp.iinfo(jnp.int64).min
        hi = jnp.iinfo(jnp.int64).max
    if spec.func is AggFunc.MIN:
        keyed = jnp.where(valid, lane, hi)
        best_lane = K.seg_min(keyed, seg, nseg)
    else:
        keyed = jnp.where(valid, lane, lo)
        best_lane = K.seg_max(keyed, seg, nseg)
    # recover a row index holding the winning lane value for exact value gather
    is_best = valid & (keyed == jnp.take(best_lane, seg))
    best_pos = K.seg_min(jnp.where(is_best, pos, jnp.int32(cap)), seg, nseg)
    best_pos = jnp.clip(best_pos, 0, cap - 1)
    out_val = jnp.take(sv, best_pos)
    return DeviceColumn(spec.out_dtype, out_val, all_null, spec.out_dict)


def _global_aggregate(env: Env, aggs: list[AggSpec], out_schema: T.Schema,
                      live: jax.Array) -> DeviceBatch:
    """No GROUP BY: plain masked reductions — no segment scatter (the old path
    scattered into `capacity` segments to produce ONE row, allocating and
    reducing an input-sized output per aggregate; warm SF1 Q6 spent ~2.7s
    there). Emits exactly one row even over empty input (SQL: COUNT=0,
    SUM=NULL); output capacity MIN_CAPACITY."""
    from igloo_tpu.exec.batch import MIN_CAPACITY

    def one_row(scalar, dtype, is_null=None):
        lane = jnp.zeros((MIN_CAPACITY,), dtype=dtype).at[0].set(
            scalar.astype(dtype))
        nl = None
        if is_null is not None:
            nl = jnp.zeros((MIN_CAPACITY,), dtype=bool).at[0].set(is_null)
        return lane, nl

    out_cols: list[DeviceColumn] = []
    for spec in aggs:
        if spec.func is AggFunc.COUNT_STAR:
            lane, _ = one_row(jnp.sum(live.astype(jnp.int64)), jnp.int64)
            out_cols.append(DeviceColumn(T.INT64, lane, None, None))
            continue
        v, nl = spec.arg.fn(env)
        valid = live if nl is None else (live & ~nl)
        n_valid = jnp.sum(valid.astype(jnp.int64))
        all_null = n_valid == 0
        if spec.func is AggFunc.COUNT:
            lane, _ = one_row(n_valid, jnp.int64)
            out_cols.append(DeviceColumn(T.INT64, lane, None, None))
        elif spec.func in (AggFunc.SUM, AggFunc.AVG):
            acc_dtype = jnp.float64 if (spec.out_dtype.is_float or
                                        spec.func is AggFunc.AVG) else jnp.int64
            total = jnp.sum(jnp.where(valid, v.astype(acc_dtype),
                                      jnp.zeros((), acc_dtype)))
            if spec.func is AggFunc.AVG:
                denom = jnp.where(all_null, 1, n_valid).astype(jnp.float64)
                lane, nlo = one_row(total / denom, jnp.float64, all_null)
                out_cols.append(DeviceColumn(T.FLOAT64, lane, nlo, None))
            else:
                lane, nlo = one_row(total, spec.out_dtype.device_dtype(),
                                    all_null)
                out_cols.append(DeviceColumn(spec.out_dtype, lane, nlo, None))
        else:  # MIN / MAX with exact winning-row gather (NaN stays NaN)
            cmp_src = v
            if spec.order_arg is not None:
                cmp_src, _ = spec.order_arg.fn(env)
            if spec.arg.dtype.is_float:
                vnorm, nan = K.normalize_float(cmp_src)
                lane_v = jnp.where(nan, jnp.asarray(jnp.inf, vnorm.dtype),
                                   vnorm)
                lo = jnp.asarray(-jnp.inf, lane_v.dtype)
                hi = jnp.asarray(jnp.inf, lane_v.dtype)
            else:
                lane_v = cmp_src.astype(jnp.int64)
                lo = jnp.iinfo(jnp.int64).min
                hi = jnp.iinfo(jnp.int64).max
            keyed = jnp.where(valid, lane_v,
                              hi if spec.func is AggFunc.MIN else lo)
            best = jnp.argmin(keyed) if spec.func is AggFunc.MIN \
                else jnp.argmax(keyed)
            lane, nlo = one_row(jnp.take(v, best),
                                spec.out_dtype.device_dtype(), all_null)
            out_cols.append(DeviceColumn(spec.out_dtype, lane, nlo,
                                         spec.out_dict))
    out_live = jnp.zeros((MIN_CAPACITY,), dtype=bool).at[0].set(True)
    return DeviceBatch(out_schema, out_cols, out_live)


def _direct_aggregate(env: Env, groups: list[Compiled], gvals, gnulls,
                      aggs: list[AggSpec], out_schema: T.Schema,
                      live: jax.Array,
                      seg_dims: tuple) -> DeviceBatch:  # ((count, offset), ...)
    """Direct-scatter grouping for small indexable keys (see seg_dims_for):
    segment id = mixed-radix combination of (NULL?0:key+1) digits. Skips the
    full-capacity lex sort; output capacity = padded segment count (small)."""
    from igloo_tpu.exec.batch import round_capacity
    cap = live.shape[0]
    prod = 1
    for d, _off in seg_dims:
        prod *= d
    nseg = round_capacity(prod + 1)
    dead = nseg - 1  # dead rows land here; >= prod, never a real key combo
    seg = jnp.zeros((cap,), dtype=jnp.int32)
    for v, nl, (d, off) in zip(gvals, gnulls, seg_dims):
        comp = (v - off).astype(jnp.int32) + 1 if off else \
            v.astype(jnp.int32) + 1
        if nl is not None:
            comp = jnp.where(nl, 0, comp)
        seg = seg * jnp.int32(d) + comp
    seg = jnp.where(live, seg, jnp.int32(dead))

    counts = K.seg_sum(live.astype(jnp.int32), seg, nseg)
    group_mask = (counts > 0) & (jnp.arange(nseg) < prod)

    # group VALUES decode from the segment index (every seg_dims kind is a
    # bijection of its digit): no first-occurrence seg_min scatter — at
    # dense-int scale (6M segments over 8M lanes) each scatter is ~1 s on TPU
    out_cols: list[DeviceColumn] = []
    segid = jnp.arange(nseg, dtype=jnp.int64)
    digits = []
    rest = segid
    for d, _off in reversed(seg_dims):
        digits.append(rest % d)
        rest = rest // d
    digits.reverse()
    for digit, (d, off), g, nl in zip(digits, seg_dims, groups, gnulls):
        raw = jnp.clip(digit - 1, 0, d - 2) + off
        out_cols.append(DeviceColumn(
            g.dtype, raw.astype(g.dtype.device_dtype()),
            (digit == 0) if nl is not None else None,
            g.out_dict))
    for spec in aggs:
        out_cols.append(_reduce_one(spec, env, None, seg, live, cap, nseg))

    # compact live groups to the front (segment-id order = NULL-first
    # dictionary-rank order); aggregate output row order is not semantic
    perm_small = K.compact_perm(group_mask)
    n_groups = jnp.sum(group_mask.astype(jnp.int32))
    out_cols = [DeviceColumn(c.dtype, jnp.take(c.values, perm_small),
                             jnp.take(c.nulls, perm_small)
                             if c.nulls is not None else None, c.dictionary)
                for c in out_cols]
    out_live = jnp.arange(nseg, dtype=jnp.int32) < n_groups
    return DeviceBatch(out_schema, out_cols, out_live)


def _pallas_hash_aggregate(env: Env, groups: list[Compiled], gvals, gnulls,
                           aggs: list[AggSpec], out_schema: T.Schema,
                           live: jax.Array, pack_spec: tuple,
                           pallas_agg: tuple, consts: tuple):
    """Sort-free grouping for fully-packable keys via the one-pass Pallas
    hash aggregation (exec/pallas_kernels.hash_segagg through the dispatch
    layer): the packed lane is an exact group id, the kernel builds a
    bounded hash table over it and accumulates every aggregate in the same
    blocked pass over the input — no lex_argsort, no per-agg scatter.
    Returns (DeviceBatch, overflow flag); a True flag (bucket exhaustion:
    more distinct keys than table ways) means the caller must discard the
    result and re-run the sort path (executor deferred-flag protocol).

    Per-agg semantics mirror `_reduce_one` exactly: int sums accumulate in
    int64 (wraparound cancels), float/AVG sums in float64 (accumulation
    ORDER differs from the sorted segment reduction, so float totals may
    differ in the last ulps), MIN/MAX reduce a comparable lane and gather
    the ORIGINAL value at the first winning row position."""
    spec, packed_idx = pack_spec
    packed = K.pack_key_lane(spec, [gvals[i] for i in packed_idx],
                             [gnulls[i] for i in packed_idx], consts)
    cap = live.shape[0]

    ops: list = []
    op_inputs: list = []
    per_spec: list = []  # post-kernel assembly recipe per AggSpec

    def add_op(op, *arrays):
        ops.append(op)
        op_inputs.extend(arrays)

    for a in aggs:
        if a.func is AggFunc.COUNT_STAR:
            per_spec.append(("count_star",))
            continue
        v, nl = a.arg.fn(env)
        valid = live if nl is None else (live & ~nl)
        if nl is None:
            # null-free arg: its valid-count IS the kernel's built-in
            # live-count table — skip the redundant count op (ci=None)
            ci = None
        else:
            ci = len(ops)
            add_op("count", valid)
        if a.func is AggFunc.COUNT:
            per_spec.append(("count", ci))
            continue
        if a.func in (AggFunc.SUM, AggFunc.AVG):
            acc_dtype = jnp.float64 if (a.out_dtype.is_float or
                                        a.func is AggFunc.AVG) else jnp.int64
            sval = jnp.where(valid, v.astype(acc_dtype),
                             jnp.zeros((), acc_dtype))
            si = len(ops)
            add_op("sum", valid, sval)
            per_spec.append(("avg" if a.func is AggFunc.AVG else "sum",
                             ci, si))
            continue
        # MIN / MAX: comparable lane like _reduce_one; the kernel tracks the
        # first winning row position for the exact original-value gather
        cmp_src = v
        if a.order_arg is not None:
            cmp_src, _ = a.order_arg.fn(env)
        if a.arg.dtype.is_float:
            vnorm, nan = K.normalize_float(cmp_src)
            lane = jnp.where(nan, jnp.asarray(jnp.inf, vnorm.dtype), vnorm)
        else:
            lane = cmp_src.astype(jnp.int64)
        mi = len(ops)
        add_op("min" if a.func is AggFunc.MIN else "max", valid, lane)
        per_spec.append(("minmax", ci, mi, v))

    # per-op output-table offsets (count/sum: 1 table; min/max: value + pos)
    op_out = []
    oi = 0
    for op in ops:
        op_out.append(oi)
        oi += 2 if op in ("min", "max") else 1

    key_table, live_cnt, tables, ovf = dispatch.segagg(
        pallas_agg, packed, live, tuple(ops), op_inputs)
    nseg = key_table.shape[0]
    group_mask = key_table != dispatch.EMPTY_KEY

    # group key columns decode from the stored packed key (pack_key_lane's
    # all-ascending nulls-first encoding is invertible; offsets ride consts).
    # Digit j belongs to groups[packed_idx[j]] — identity for a full-cover
    # pack, but realign explicitly.
    dvals, dnulls = K.unpack_key_digits(spec, key_table, consts)
    kvals = [None] * len(groups)
    knulls = [None] * len(groups)
    for j, i in enumerate(packed_idx):
        kvals[i], knulls[i] = dvals[j], dnulls[j]
    out_cols: list[DeviceColumn] = []
    for v, nl_flag, g, nl in zip(kvals, knulls, groups, gnulls):
        out_cols.append(DeviceColumn(
            g.dtype, v.astype(g.dtype.device_dtype()),
            nl_flag if nl is not None else None, g.out_dict))

    def n_valid_of(ci):
        return live_cnt if ci is None else tables[op_out[ci]]

    for a, rec in zip(aggs, per_spec):
        if rec[0] == "count_star":
            out_cols.append(DeviceColumn(T.INT64, live_cnt, None, None))
            continue
        n_valid = n_valid_of(rec[1])
        all_null = n_valid == 0
        if rec[0] == "count":
            out_cols.append(DeviceColumn(T.INT64, n_valid, None, None))
        elif rec[0] == "sum":
            total = tables[op_out[rec[2]]]
            out_cols.append(DeviceColumn(
                a.out_dtype, total.astype(a.out_dtype.device_dtype()),
                all_null, None))
        elif rec[0] == "avg":
            total = tables[op_out[rec[2]]]
            denom = jnp.where(all_null, 1, n_valid).astype(jnp.float64)
            out_cols.append(DeviceColumn(T.FLOAT64, total / denom,
                                         all_null, None))
        else:  # minmax: exact original value at the first winning position
            best_pos = tables[op_out[rec[2]] + 1]
            out_val = jnp.take(rec[3], jnp.clip(best_pos, 0, cap - 1))
            out_cols.append(DeviceColumn(a.out_dtype, out_val, all_null,
                                         a.out_dict))

    # compact live groups to the front (slot order; aggregate output row
    # order is not semantic)
    perm_small = K.compact_perm(group_mask)
    n_groups = jnp.sum(group_mask.astype(jnp.int32))
    out_cols = [DeviceColumn(c.dtype, jnp.take(c.values, perm_small),
                             jnp.take(c.nulls, perm_small)
                             if c.nulls is not None else None, c.dictionary)
                for c in out_cols]
    out_live = jnp.arange(nseg, dtype=jnp.int32) < n_groups
    return DeviceBatch(out_schema, out_cols, out_live), ovf


def distinct_batch(batch: DeviceBatch) -> DeviceBatch:
    """SELECT DISTINCT: group by every column, no aggregates."""
    groups = []
    for i, (f, c) in enumerate(zip(batch.schema, batch.columns)):
        comp = Compiled(lambda env, _i=i: (env.values[_i], env.nulls[_i]),
                        f.dtype, c.dictionary)
        groups.append(comp)
    return aggregate_batch(batch, groups, [], batch.schema)
