"""Group-by / aggregate kernel.

The reference has NO aggregation in its custom engine (DataFusion handles it on the
working path; the custom physical planner lowers only scan/filter/project/join,
physical_planner.rs:23-140). This is the TPU design from SURVEY.md §7 step 4:
sort-based segment reduction — one fused XLA computation, static shapes:

    keys -> lexicographic stable argsort -> contiguous groups -> boundary flags
         -> segment_sum/min/max over static segment count (= capacity)

Output capacity equals input capacity; row `i` of the output is group `i`
(compacted to the front, `live` marks real groups). No hashing: grouping equality
is exact lane comparison after the sort, so no collision handling is needed.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from igloo_tpu import types as T
from igloo_tpu.exec import kernels as K
from igloo_tpu.exec.batch import DeviceBatch, DeviceColumn, DictInfo
from igloo_tpu.exec.expr_compile import Compiled, Env
from igloo_tpu.plan.expr import AggFunc


@dataclass(frozen=True)
class AggSpec:
    func: AggFunc
    arg: Optional[Compiled]       # None only for COUNT_STAR
    out_dtype: T.DataType
    out_dict: Optional[DictInfo]  # MIN/MAX over strings keep the arg dictionary


def aggregate_batch(batch: DeviceBatch, groups: list[Compiled],
                    aggs: list[AggSpec], out_schema: T.Schema,
                    consts: tuple = ()) -> DeviceBatch:
    """Pure, jit-traceable: DeviceBatch -> DeviceBatch of one row per group.
    Output columns carry no dictionaries — the executor re-attaches them."""
    env = Env.from_batch(batch, consts)
    cap = batch.capacity
    live = batch.live

    # evaluate group keys once
    gvals: list[jax.Array] = []
    gnulls: list[Optional[jax.Array]] = []
    for g in groups:
        v, nl = g.fn(env)
        gvals.append(v)
        gnulls.append(nl)

    if groups:
        # equality lanes (string ids are already ranks; floats decompose into
        # nan-flag + normalized-value lanes — no 64-bit bitcasts, TPU-safe)
        flat_lanes: list = []
        flat_nulls: list = []
        sort_lanes: list = []
        for v, nl, g in zip(gvals, gnulls, groups):
            for lane in K.group_lanes_for(v, g.dtype.is_float):
                flat_lanes.append(lane)
                flat_nulls.append(nl)
            sort_lanes.extend(K.sort_lanes_for(v, nl, g.dtype.is_float, True, False))
        perm = K.lex_argsort(sort_lanes, live)
        s_live = jnp.take(live, perm)
        s_lanes = [jnp.take(l, perm) for l in flat_lanes]
        s_nulls = [jnp.take(nl, perm) if nl is not None else None
                   for nl in flat_nulls]
        seg, start = K.group_segments(s_lanes, s_nulls, s_live)
        num_groups = jnp.sum(start.astype(jnp.int32))
    else:
        # global aggregate: one group holding every live row; emit exactly one
        # output row even over empty input (SQL: COUNT=0, SUM=NULL)
        perm = jnp.arange(cap, dtype=jnp.int32)
        s_live = live
        seg = jnp.zeros((cap,), dtype=jnp.int32)
        start = jnp.zeros((cap,), dtype=bool).at[0].set(True)
        num_groups = jnp.int32(1)

    # first sorted row of each segment (for group representative values)
    pos = jnp.arange(cap, dtype=jnp.int32)
    big = jnp.int32(cap)
    first_pos = jax.ops.segment_min(jnp.where(s_live, pos, big), seg,
                                    num_segments=cap)
    first_pos = jnp.clip(first_pos, 0, cap - 1)

    out_cols: list[DeviceColumn] = []
    # group key output columns
    for v, nl, g in zip(gvals, gnulls, groups):
        sv = jnp.take(jnp.take(v, perm), first_pos)
        snl = jnp.take(jnp.take(nl, perm), first_pos) if nl is not None else None
        # out_dict here is trace-time metadata: correct for eager (direct) use;
        # under the executor's jit cache it may be stale on a cache hit, so the
        # executor re-attaches current dictionaries after every call
        out_cols.append(DeviceColumn(g.dtype, sv.astype(g.dtype.device_dtype())
                                     if sv.dtype != g.dtype.device_dtype() else sv,
                                     snl, g.out_dict))

    # aggregates via segment reductions over sorted order
    for spec in aggs:
        out_cols.append(_reduce_one(spec, env, perm, seg, s_live, cap))

    out_live = jnp.arange(cap, dtype=jnp.int32) < num_groups
    return DeviceBatch(out_schema, out_cols, out_live)


def _reduce_one(spec: AggSpec, env: Env, perm, seg, s_live, cap) -> DeviceColumn:
    if spec.func is AggFunc.COUNT_STAR:
        cnt = jax.ops.segment_sum(s_live.astype(jnp.int64), seg, num_segments=cap)
        return DeviceColumn(T.INT64, cnt, None, None)

    v, nl = spec.arg.fn(env)
    sv = jnp.take(v, perm)
    snl = jnp.take(nl, perm) if nl is not None else None
    valid = s_live if snl is None else (s_live & ~snl)
    n_valid = jax.ops.segment_sum(valid.astype(jnp.int64), seg, num_segments=cap)
    all_null = n_valid == 0

    if spec.func is AggFunc.COUNT:
        return DeviceColumn(T.INT64, n_valid, None, None)

    if spec.func is AggFunc.SUM or spec.func is AggFunc.AVG:
        acc_dtype = jnp.float64 if (spec.out_dtype.is_float or
                                    spec.func is AggFunc.AVG) else jnp.int64
        sval = jnp.where(valid, sv.astype(acc_dtype), jnp.zeros((), acc_dtype))
        total = jax.ops.segment_sum(sval, seg, num_segments=cap)
        if spec.func is AggFunc.AVG:
            denom = jnp.where(all_null, 1, n_valid).astype(jnp.float64)
            return DeviceColumn(T.FLOAT64, total / denom, all_null, None)
        return DeviceColumn(spec.out_dtype,
                            total.astype(spec.out_dtype.device_dtype()),
                            all_null, None)

    # MIN / MAX: sentinel-masked segment reduce on a comparable lane, then an
    # exact gather of the original value at a winning position (so e.g. a NaN
    # winner comes back as NaN, not as its +inf ordering surrogate)
    pos = jnp.arange(cap, dtype=jnp.int32)
    if spec.arg.dtype.is_float:
        vnorm, nan = K.normalize_float(sv)
        lane = jnp.where(nan, jnp.asarray(jnp.inf, vnorm.dtype), vnorm)
        lo = jnp.asarray(-jnp.inf, lane.dtype)
        hi = jnp.asarray(jnp.inf, lane.dtype)
    else:
        lane = sv.astype(jnp.int64)
        lo = jnp.iinfo(jnp.int64).min
        hi = jnp.iinfo(jnp.int64).max
    if spec.func is AggFunc.MIN:
        keyed = jnp.where(valid, lane, hi)
        best_lane = jax.ops.segment_min(keyed, seg, num_segments=cap)
    else:
        keyed = jnp.where(valid, lane, lo)
        best_lane = jax.ops.segment_max(keyed, seg, num_segments=cap)
    # recover a row index holding the winning lane value for exact value gather
    is_best = valid & (keyed == jnp.take(best_lane, seg))
    best_pos = jax.ops.segment_min(jnp.where(is_best, pos, jnp.int32(cap)), seg,
                                   num_segments=cap)
    best_pos = jnp.clip(best_pos, 0, cap - 1)
    out_val = jnp.take(sv, best_pos)
    return DeviceColumn(spec.out_dtype, out_val, all_null, spec.out_dict)


def distinct_batch(batch: DeviceBatch) -> DeviceBatch:
    """SELECT DISTINCT: group by every column, no aggregates."""
    groups = []
    for i, (f, c) in enumerate(zip(batch.schema, batch.columns)):
        comp = Compiled(lambda env, _i=i: (env.values[_i], env.nulls[_i]),
                        f.dtype, c.dictionary)
        groups.append(comp)
    return aggregate_batch(batch, groups, [], batch.schema)
