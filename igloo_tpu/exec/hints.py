"""Persistent cardinality-hint store for adaptive fused execution.

The fused compiler (exec/fused.py) sizes intermediate compactions from
observed live counts. In-memory hints die with the process, which would make
every fresh process pay the un-hinted full-width program AND a second XLA
compile once hints arrive. Persisting them beside the XLA compilation cache
means a new process compiles the hinted program directly — and hits the
persistent XLA cache for it.

Keys are structural node fingerprints (nested tuples); they are stored under a
stable content hash of their repr. A hash collision or stale entry can only
mis-SIZE a compaction, never corrupt a result: the in-program overflow flag
triggers an exact repair re-run (see FusedCompiler._adaptive)."""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
from typing import Optional

# lock discipline (checked by igloo-lint lock-discipline): one HintStore is
# shared by every executor the engine builds, and `put`/`flush` run both on
# the query thread and on the GRACE prefetch thread; `_data`/`_dirty`
# read-modify-writes must hold the store lock
_GUARDED_BY = {"_lock": ("_data", "_dirty")}


def _digest(key) -> str:
    return hashlib.sha1(repr(key).encode()).hexdigest()


class HintStore:
    def __init__(self, path: Optional[str]):
        self._path = path
        self._lock = threading.Lock()
        self._data: dict[str, int] = {}
        self._dirty = False
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._data = {k: int(v) for k, v in json.load(f).items()}
            except Exception:
                self._data = {}

    def get(self, key) -> Optional[int]:
        with self._lock:
            return self._data.get(_digest(key))

    def put(self, key, n: int) -> None:
        d = _digest(key)
        with self._lock:
            if self._data.get(d) != n:
                self._data[d] = int(n)
                self._dirty = True

    def remove(self, key) -> None:
        with self._lock:
            if self._data.pop(_digest(key), None) is not None:
                self._dirty = True

    def flush(self) -> None:
        # the file write stays INSIDE the lock: two racing flushes (query
        # thread + GRACE prefetch thread) could otherwise os.replace an older
        # snapshot over a newer one, silently dropping a just-adopted hint
        with self._lock:
            if not self._dirty or not self._path:
                return
            self._dirty = False
            try:
                os.makedirs(os.path.dirname(self._path), exist_ok=True)
                fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self._path))
                with os.fdopen(fd, "w") as f:
                    json.dump(self._data, f)
                os.replace(tmp, self._path)
            except Exception:
                pass  # hints are an optimization; never fail a query on them


def default_store() -> HintStore:
    """Store beside the persistent XLA cache (same enable/disable knob)."""
    from igloo_tpu import compile_cache
    cache_dir = compile_cache.active_dir()
    return HintStore(os.path.join(cache_dir, "nhints.json")
                     if cache_dir else None)
