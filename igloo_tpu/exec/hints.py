"""Persistent per-plan-fingerprint stores for adaptive execution.

Two stores share one digest-keyed JSON-file idiom:

- `HintStore` (PR1/round-4 era): flat int live-count hints for the fused /
  staged compilers' in-program compactions, keyed by *compiler-internal*
  fingerprints (exec/fused.py hfps, exec/executor.py slive keys).
- `AdaptiveStats` (the telemetry->planner feedback loop, docs/adaptive.md):
  per-*logical-subtree* observed execution statistics — output cardinality,
  join input rows (selectivity), exchange result bytes, and a top-bucket skew
  sketch — keyed by `plan_fp` structural fingerprints. Planners consume them:
  join reordering (plan/optimizer.py), broadcast-vs-shuffle switching
  (cluster/fragment.py), hot-key salting (cluster/exchange.py), and the mesh
  tier's broadcast rule (parallel/executor.py).

Safety contract (both stores): keys are structural fingerprints stored under
a stable content hash of their repr. A hash collision or stale entry can only
mis-SIZE or mis-ROUTE a plan choice — pick a worse join order, broadcast or
salt when it no longer pays — never corrupt a result: every consumer's
output is semantics-preserving for any stats value, and in-program
compactions keep their overflow-flag exact-repair path
(FusedCompiler._adaptive). Note that scan fingerprints key by table NAME +
pushed filters + partition, not content: re-registering different data
under the same name keeps old entries, which — by the same contract — can
only mis-route plans until fresh observations overwrite them.

Persisting beside the XLA compilation cache means a new process plans from
the cluster's observed history directly — and hits the persistent XLA cache
for the programs those plans compile to."""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
from typing import Optional

# lock discipline (checked by igloo-lint lock-discipline): one store instance
# is shared by every executor the engine builds, and `put`/`observe`/`flush`
# run both on the query thread and on worker threads (GRACE prefetch, Flight
# RPC handlers); `_data`/`_dirty` read-modify-writes must hold the store lock
_GUARDED_BY = {"_lock": ("_data", "_dirty")}

#: kill switch for the whole telemetry->planner loop: IGLOO_ADAPTIVE=0
#: reproduces pre-adaptive plans (join order, exchange shape) exactly
ADAPTIVE_ENV = "IGLOO_ADAPTIVE"


def adaptive_enabled() -> bool:
    return os.environ.get(ADAPTIVE_ENV, "1") != "0"


def _digest(key) -> str:
    return hashlib.sha1(repr(key).encode()).hexdigest()


def digest_key(key) -> str:
    """Public stable digest of a fingerprint key — what rides the wire when a
    planner tags fragments for the coordinator's end-of-query recording."""
    return _digest(key)


class _JsonStore:
    """Digest-keyed JSON-file store base: atomic flush, never fails a query."""

    def __init__(self, path: Optional[str]):
        self._path = path
        self._lock = threading.Lock()
        self._data: dict = {}
        self._dirty = False
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._data = self._coerce(json.load(f))
            except Exception:
                self._data = {}

    def _coerce(self, raw: dict) -> dict:  # subclass value validation
        return dict(raw)

    def flush(self) -> None:
        # the file write stays INSIDE the lock: two racing flushes (query
        # thread + GRACE prefetch thread) could otherwise os.replace an older
        # snapshot over a newer one, silently dropping a just-adopted entry
        with self._lock:
            if not self._dirty or not self._path:
                return
            self._dirty = False
            try:
                os.makedirs(os.path.dirname(self._path), exist_ok=True)
                fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self._path))
                with os.fdopen(fd, "w") as f:
                    json.dump(self._data, f)
                os.replace(tmp, self._path)
            except Exception:
                pass  # stats are an optimization; never fail a query on them


class HintStore(_JsonStore):
    """Flat int hints (live counts / sentinels) for the fused/staged tiers."""

    def _coerce(self, raw: dict) -> dict:
        return {k: int(v) for k, v in raw.items()}

    def get(self, key) -> Optional[int]:
        with self._lock:
            return self._data.get(_digest(key))

    def put(self, key, n: int) -> None:
        d = _digest(key)
        with self._lock:
            if self._data.get(d) != n:
                self._data[d] = int(n)
                self._dirty = True

    def remove(self, key) -> None:
        with self._lock:
            if self._data.pop(_digest(key), None) is not None:
                self._dirty = True


class AdaptiveStats(_JsonStore):
    """Observed execution statistics per logical-subtree fingerprint.

    Record fields (all optional, merged per observation):
      rows       observed output cardinality of the subtree
      in_rows    sum of join input cardinalities (rows/in_rows = selectivity)
      bytes      observed Arrow result bytes (exchange fragments)
      max_share  top-bucket share of the subtree's hash exchange (skew sketch,
                 from the fragment store's existing per-bucket rows metadata)
      hot_bucket index of that top bucket
      nbuckets   bucket count the sketch was taken at (a sketch only guides
                 salting when the current plan uses the same bucket count —
                 the hash is deterministic per count, not across counts)
      peak_hbm_bytes  observed device-memory watermark after running the
                 subtree (an UPPER bound — the watermark is process-
                 cumulative); the serving admission gate's footprint
                 prediction (cluster/serving.py, docs/serving.md)
    """

    _FIELDS = ("rows", "in_rows", "bytes", "max_share", "hot_bucket",
               "nbuckets", "peak_hbm_bytes")

    def _coerce(self, raw: dict) -> dict:
        out = {}
        for k, v in raw.items():
            if isinstance(v, dict):
                out[k] = {f: v[f] for f in self._FIELDS if f in v}
        return out

    # NOTE: `observed`/`observed_rows` return raw data-dependent values —
    # they are taint SOURCES for the igloo-lint jit-key checker: their
    # results must never reach a _jitted fingerprint unquantized (they drive
    # plan-structure and routing choices, not program shapes).
    def observed(self, key) -> Optional[dict]:
        with self._lock:
            rec = self._data.get(_digest(key))
            return dict(rec) if rec is not None else None

    def observed_rows(self, key) -> Optional[int]:
        rec = self.observed(key)
        v = rec.get("rows") if rec else None
        return int(v) if v is not None else None

    def selectivity(self, key) -> Optional[float]:
        """Observed rows-out / rows-in, when both were recorded."""
        rec = self.observed(key)
        if not rec or not rec.get("in_rows") or rec.get("rows") is None:
            return None
        return rec["rows"] / rec["in_rows"]

    def observe(self, key, **fields) -> None:
        self.observe_by_digest(_digest(key), **fields)

    def observe_by_digest(self, digest: str, **fields) -> None:
        """Merge non-None fields into the record (last observation wins —
        stale values can only mis-route, see module docstring)."""
        clean = {k: v for k, v in fields.items()
                 if k in self._FIELDS and v is not None}
        if not clean:
            return
        with self._lock:
            rec = self._data.get(digest)
            if rec is None:
                rec = {}
                self._data[digest] = rec
            for k, v in clean.items():
                if rec.get(k) != v:
                    rec[k] = v
                    self._dirty = True

    def remove(self, key) -> None:
        with self._lock:
            if self._data.pop(_digest(key), None) is not None:
                self._dirty = True


class BaselineStats(_JsonStore):
    """Rolling per-fingerprint performance baselines for the watchtower's
    anomaly detector (docs/observability.md#watchtower): bounded windows of
    observed wall seconds, peak-HBM bytes, and exchange bytes per `plan_fp`
    key. Quantiles are computed from the window at read time — a WINDOW of
    64 keeps every digest a few hundred bytes in the JSON file while P99
    still reflects the recent regime, and a plan whose cost legitimately
    shifts (data grew) re-baselines itself within one window.

    Same safety contract as AdaptiveStats: a stale or collided baseline can
    only mis-CLASSIFY a query as slow/normal — escalation captures extra
    telemetry, it never changes a plan or a result."""

    _FIELDS = ("wall_s", "hbm_bytes", "exchange_bytes")
    WINDOW = 64

    def _coerce(self, raw: dict) -> dict:
        out = {}
        for k, v in raw.items():
            if not isinstance(v, dict):
                continue
            rec: dict = {"count": int(v.get("count", 0))}
            for f in self._FIELDS:
                vals = v.get(f)
                if isinstance(vals, list):
                    rec[f] = [float(x) for x in vals][-self.WINDOW:]
            out[k] = rec
        return out

    def observe(self, key, wall_s: Optional[float] = None,
                hbm_bytes: Optional[float] = None,
                exchange_bytes: Optional[float] = None) -> None:
        fields = {"wall_s": wall_s, "hbm_bytes": hbm_bytes,
                  "exchange_bytes": exchange_bytes}
        clean = {k: float(v) for k, v in fields.items() if v is not None}
        if not clean:
            return
        d = _digest(key)
        with self._lock:
            rec = self._data.setdefault(d, {"count": 0})
            rec["count"] = int(rec.get("count", 0)) + 1
            for f, v in clean.items():
                window = rec.setdefault(f, [])
                window.append(v)
                del window[:-self.WINDOW]
            self._dirty = True

    @staticmethod
    def _quantile(vals: list, q: float) -> float:
        if not vals:
            return 0.0
        s = sorted(vals)
        idx = min(max(int(q * len(s) + 0.999999) - 1, 0), len(s) - 1)
        return s[idx]

    def baseline(self, key) -> dict:
        """Digest summary: observation count plus P50/P99 of each window
        (0.0 where nothing was observed)."""
        with self._lock:
            rec = self._data.get(_digest(key))
            rec = {k: (list(v) if isinstance(v, list) else v)
                   for k, v in rec.items()} if rec else {}
        out = {"count": int(rec.get("count", 0))}
        for f in self._FIELDS:
            vals = rec.get(f) or []
            out[f"{f}_p50"] = self._quantile(vals, 0.50)
            out[f"{f}_p99"] = self._quantile(vals, 0.99)
        return out


def row_width_bytes(schema) -> int:
    """Estimated bytes per row for observed-rows -> bytes conversion. The
    join reorder (plan/optimizer.py) and the broadcast switch
    (cluster/fragment.py) must agree on what a row weighs, so the heuristic
    lives here, next to the store both read."""
    return max(8, sum(16 if f.dtype.is_string else 8 for f in schema))


# --- structural plan fingerprints -------------------------------------------


def plan_fp(plan):
    """Projection-INSENSITIVE structural fingerprint of a logical subtree:
    expressions repr by column NAME (not index), scans by (table, filters,
    partition). The same logical work keys the same entry whether observed
    pre- or post-pruning, on the host tier, the device tier, or a cluster
    fragment. Returns None for shapes with no stable key (subqueries,
    windows, unions...). Shared by the host tier's structural memo and every
    AdaptiveStats producer/consumer."""
    from igloo_tpu.plan import logical as L

    def xr(x) -> Optional[str]:
        # exprs repr by name; a nested subquery reprs as the OPAQUE
        # "subquery(...)" (two different subqueries would collide) ->
        # poison the fingerprint
        r = repr(x)
        return None if "subquery(" in r or "exists(" in r else r

    t = type(plan)
    if t is L.Scan:
        fr = xr(plan.pushed_filters)
        return fr and ("scan", plan.table, fr, plan.partition)
    if t is L.Filter:
        sub = plan_fp(plan.input)
        pr = xr(plan.predicate)
        return sub and pr and ("filter", pr, sub)
    if t is L.Project:
        sub = plan_fp(plan.input)
        er = xr(plan.exprs)
        return sub and er and ("proj", er, tuple(plan.names), sub)
    if t is L.Join:
        ls, rs = plan_fp(plan.left), plan_fp(plan.right)
        kr = xr((plan.left_keys, plan.right_keys, plan.residual))
        return ls and rs and kr and (
            "join", plan.join_type.value, kr, ls, rs)
    if t is L.Aggregate:
        sub = plan_fp(plan.input)
        ar = xr((plan.group_exprs, plan.aggs))
        return sub and ar and ("agg", ar, tuple(plan.agg_names), sub)
    if t is L.Distinct:
        sub = plan_fp(plan.input)
        return sub and ("distinct", sub)
    if t is L.Sort:
        # ORDER BY must not poison the key: production queries near-always
        # sort their output, and an unkeyed plan gets no latency baseline
        # (docs/observability.md#watchtower)
        sub = plan_fp(plan.input)
        kr = xr((plan.keys, plan.ascending, plan.nulls_first))
        return sub and kr and ("sort", kr, sub)
    if t is L.Limit:
        sub = plan_fp(plan.input)
        return sub and ("limit", plan.limit, plan.offset, sub)
    return None  # unbounded/unhandled shapes: no stable key


# --- default instances -------------------------------------------------------


def default_store() -> HintStore:
    """Store beside the persistent XLA cache (same enable/disable knob)."""
    from igloo_tpu import compile_cache
    cache_dir = compile_cache.active_dir()
    return HintStore(os.path.join(cache_dir, "nhints.json")
                     if cache_dir else None)


_adaptive_singleton_lock = threading.Lock()
_adaptive_singleton: Optional[AdaptiveStats] = None

ADAPTIVE_PATH_ENV = "IGLOO_ADAPTIVE_STATS"


def adaptive_store() -> AdaptiveStats:
    """Process-wide AdaptiveStats: engine, coordinator planner, and mesh tier
    all feed and read ONE store. Path precedence: IGLOO_ADAPTIVE_STATS env >
    beside the persistent XLA cache > in-memory only (still adaptive within
    the process; nothing persists)."""
    global _adaptive_singleton
    with _adaptive_singleton_lock:
        if _adaptive_singleton is None:
            path = os.environ.get(ADAPTIVE_PATH_ENV)
            if path is None:
                from igloo_tpu import compile_cache
                cache_dir = compile_cache.active_dir()
                if cache_dir:
                    path = os.path.join(cache_dir, "adaptive_stats.json")
            _adaptive_singleton = AdaptiveStats(path or None)
        return _adaptive_singleton


def reset_adaptive_store() -> None:
    """Drop the process singleton (tests re-point IGLOO_ADAPTIVE_STATS)."""
    global _adaptive_singleton
    with _adaptive_singleton_lock:
        _adaptive_singleton = None


_watch_singleton_lock = threading.Lock()
_watch_singleton: Optional[BaselineStats] = None

WATCH_PATH_ENV = "IGLOO_WATCH_STATS"


def watch_store() -> BaselineStats:
    """Process-wide BaselineStats for the watchtower detector
    (utils/watch.py). Path precedence mirrors adaptive_store():
    IGLOO_WATCH_STATS env > beside the persistent XLA cache > in-memory
    only (baselines still build within the process; nothing persists)."""
    global _watch_singleton
    with _watch_singleton_lock:
        if _watch_singleton is None:
            path = os.environ.get(WATCH_PATH_ENV)
            if path is None:
                from igloo_tpu import compile_cache
                cache_dir = compile_cache.active_dir()
                if cache_dir:
                    path = os.path.join(cache_dir, "watch_baselines.json")
            _watch_singleton = BaselineStats(path or None)
        return _watch_singleton


def reset_watch_store() -> None:
    """Drop the process singleton (tests re-point IGLOO_WATCH_STATS)."""
    global _watch_singleton
    with _watch_singleton_lock:
        _watch_singleton = None
