"""HBM-resident batch cache with byte-budget LRU eviction.

The reference's cache (crates/cache/src/lib.rs:20-56) maps query strings to
RecordBatch vectors and declares a `CacheConfig{capacity}` it never enforces
(gap G7). This is the real version, adapted to the TPU memory hierarchy: the
cached value is a `DeviceBatch` whose column lanes are already resident in HBM,
so a hit skips Parquet/CSV decode, dictionary encoding, AND the host->HBM
transfer. The byte budget is enforced with LRU eviction; entries are validated
against a provider *snapshot token* so source changes invalidate stale batches
(the CDC hook — see igloo_tpu/cdc.py, replacing the reference's empty cdc
crate, crates/cdc/src/lib.rs:9).
"""
from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

from igloo_tpu.exec.batch import DeviceBatch
from igloo_tpu.utils import stats
from igloo_tpu.utils.tracing import counter


def scan_table_key(name: str) -> str:
    """Canonical cache key for a table name: the binder sets Scan.table to the
    last dotted component lowercased (plan/binder.py), so every invalidation
    path must reduce qualified catalog names ("db.tbl") the same way."""
    return name.split(".")[-1].lower()


@dataclass
class CacheEntry:
    value: object          # DeviceBatch (BatchCache) / pa.Table (ResultCache)
    snapshot: object
    nbytes: int
    tables: frozenset = frozenset()  # scanned tables (invalidate_table match)


class SnapshotLRU:
    """Thread-safe byte-budget LRU with snapshot validation — the shared core
    of the HBM scan cache (BatchCache) and the host query-result cache
    (exec/result_cache.ResultCache). Subclasses set `counter_prefix` and
    `_match_table` (how invalidate_table selects entries). `capacity` is an
    optional ENTRY-count bound enforced beside the byte budget (the
    reference's declared-but-never-enforced CacheConfig.capacity, gap G7):
    byte budgets alone let thousands of tiny entries pile up, which bloats
    every invalidation sweep."""

    counter_prefix = "cache"

    def __init__(self, budget_bytes: int = 1 << 30,
                 capacity: Optional[int] = None):
        self.budget_bytes = int(budget_bytes)
        self.capacity = int(capacity) if capacity is not None else None
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key, snapshot: object):
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                counter(f"{self.counter_prefix}.miss")
                return None
            if e.snapshot != snapshot:
                # source changed underneath us: invalidate
                self._bytes -= e.nbytes
                del self._entries[key]
                self.misses += 1
                counter(f"{self.counter_prefix}.invalidated")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            counter(f"{self.counter_prefix}.hit")
            # per-operator attribution in the query stats tree (a scan node
            # served from HBM shows cache_hit=N instead of upload bytes)
            stats.bump_attr(f"{self.counter_prefix}_hit")
            return e.value

    def put(self, key, value, snapshot: object, nbytes: int,
            tables: frozenset = frozenset()) -> None:
        if nbytes > self.budget_bytes:
            return  # larger than the whole budget: never cacheable
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = CacheEntry(value, snapshot, nbytes, tables)
            self._bytes += nbytes
            while self._bytes > self.budget_bytes and self._entries:
                _, ev = self._entries.popitem(last=False)
                self._bytes -= ev.nbytes
                self.evictions += 1
                counter(f"{self.counter_prefix}.evict")
            while self.capacity is not None and \
                    len(self._entries) > self.capacity:
                _, ev = self._entries.popitem(last=False)
                self._bytes -= ev.nbytes
                self.evictions += 1
                counter(f"{self.counter_prefix}.evicted")

    def _match_table(self, key, entry: CacheEntry, table_key: str) -> bool:
        raise NotImplementedError

    def invalidate_table(self, table: str) -> int:
        """Drop every entry sourced from `table` (CDC invalidation bus entry
        point). Returns the number of entries dropped. `table` may be a
        qualified catalog name; it is canonicalized to the scan key."""
        tk = scan_table_key(table)
        with self._lock:
            doomed = [k for k, e in self._entries.items()
                      if self._match_table(k, e, tk)]
            for k in doomed:
                self._bytes -= self._entries.pop(k).nbytes
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)


class BatchCache(SnapshotLRU):
    """HBM scan cache. Two entry shapes, both with key[0] = table name:

    - column-granular (providers with stable row order):
      (table, filter-fp, partition, 'col', name) -> (DeviceColumn, n_rows) and
      (table, filter-fp, partition, 'live')      -> live lane array;
      scans assemble batches from these so overlapping projections share the
      uploaded lanes (written via `put_entry`).
    - whole-batch (order-unstable providers, e.g. DBAPI):
      (table, projection, filter-fp, partition) -> DeviceBatch (via `put`)."""

    counter_prefix = "cache"

    def put(self, key: tuple, batch: DeviceBatch, snapshot: object) -> None:
        super().put(key, batch, snapshot, batch.nbytes())

    def put_entry(self, key: tuple, value: object, snapshot: object,
                  nbytes: int, table: str) -> None:
        """Column-granular entries; `table` must equal key[0] (invalidation)."""
        assert key and key[0] == table
        super().put(key, value, snapshot, nbytes)

    def _match_table(self, key, entry, table_key: str) -> bool:
        return bool(key) and key[0] == table_key


def provider_snapshot(provider) -> object:
    """Snapshot token for a provider: changes iff the underlying data may have
    changed. Providers may implement `snapshot()` (file connectors return
    mtimes/sizes); the fallback is provider IDENTITY, correct for immutable
    in-memory tables (re-registering a table creates a new provider).

    The identity token is a weakref, not `id()`: a bare id is reused by the
    allocator once the provider is freed, so a cache entry could validate
    against a DIFFERENT provider that happens to land on the same address —
    the exact staleness bug the GRACE partition loop hit (its providers now
    carry explicit snapshot() tokens, but any other transient provider would
    re-create it). Two live refs to the same provider compare equal; a dead
    ref compares equal only to itself, so entries for freed providers can
    never validate again."""
    snap = getattr(provider, "snapshot", None)
    if callable(snap):
        return snap()
    try:
        return weakref.ref(provider)
    except TypeError:
        # non-weakrefable (slotted C extension): identity is best-effort;
        # such providers are long-lived connector objects, not loop-allocated
        return id(provider)  # lint: allow(cache-key)
