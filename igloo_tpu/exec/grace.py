"""Out-of-core GRACE hash join: single-device execution of joins whose inputs
exceed the device-memory budget.

Round-3 verdict item 4: the chunked executor only streams decomposable
aggregates over scans (exec/chunked.py's documented ceiling) — a join over an
over-budget table unions every chunk back into one device batch. This module
lifts that ceiling the classic way, adapted to the static-shape TPU engine:

  phase 1 (partition): each side of the join is read PROVIDER-PARTITION at a
      time through the normal (fused) executor — projections/filters applied
      on device, so only surviving columns/rows come back — and the resulting
      host Arrow rows split into P buckets by a hash of the join key(s).
      No full table ever materializes on device; host buffers hold only the
      filtered, projected columns.
  phase 2 (join): for p in 0..P, the p-th buckets of both sides register as
      in-memory tables and the join subtree executes on device — equal keys
      share a bucket, so the union over p IS the join. One partition pair on
      device at a time bounds HBM by ~(input bytes / P).
  merge: a decomposable Aggregate above the join runs as per-partition
      PARTIALS (cluster/fragment.py's decomposition, shared with the
      distributed planner); the final merge + everything above (sort/limit)
      executes once over the concatenated partials. Without an aggregate the
      per-partition join results concatenate host-side and the upper plan
      runs over the union.

Supported shape (v1): [Limit] [Sort] [Project]* [Aggregate(decomposable)]
[Project/Filter]* Join(INNER equi). Anything else falls back to the normal
path unchanged. The reference has no out-of-core story at all (its operators
materialize build sides in RAM HashMaps, crates/engine/src/operators/
hash_join.rs:100-128)."""
from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa

from igloo_tpu import types as T
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.sql.ast import JoinType
from igloo_tpu.utils import tracing

MAX_GRACE_PARTITIONS = 64


def find_grace_join(plan: L.LogicalPlan, budget_bytes: int):
    """Locate the supported-shape over-budget join. Returns
    (path, agg, join, n_partitions) where `path` is the node chain from root
    down to (excluding) the join, and `agg` the decomposable Aggregate on the
    path (or None); None when the plan doesn't qualify."""
    from igloo_tpu.cluster.fragment import _DECOMPOSABLE
    from igloo_tpu.exec.chunked import estimated_lane_bytes
    path: list[L.LogicalPlan] = []
    node = plan
    agg: Optional[L.Aggregate] = None
    while True:
        if isinstance(node, (L.Limit, L.Sort, L.Project, L.Filter)):
            path.append(node)
            node = node.input
        elif isinstance(node, L.Aggregate) and agg is None and \
                not any(a.distinct for a in node.aggs) and \
                all(a.func in _DECOMPOSABLE for a in node.aggs):
            agg = node
            path.append(node)
            node = node.input
        else:
            break
    if not (isinstance(node, L.Join) and node.join_type is JoinType.INNER
            and node.left_keys):
        return None
    # all equi keys must be BARE COLUMNS hashable host-side (ints/dates);
    # expression keys and strings (cross-side dictionary alignment) fall back
    for key in node.left_keys + node.right_keys:
        if not isinstance(key, E.Column) or key.index is None:
            return None
        if key.dtype is None or not (key.dtype.is_integer
                                     or key.dtype.id == T.TypeId.DATE32):
            return None
    total = 0
    over = False
    for sc in L.walk_plan(node):
        if isinstance(sc, L.Scan) and sc.provider is not None:
            b = estimated_lane_bytes(sc.provider)
            if b is not None:
                total += b
                if b > budget_bytes:
                    over = True
    if not over:
        return None
    parts = min(MAX_GRACE_PARTITIONS, max(2, -(-total // budget_bytes)))
    return path, agg, node, parts


class GraceJoinExecutor:
    """Executes a qualifying plan partition-pair at a time (see module doc)."""

    def __init__(self, catalog, jit_cache=None, use_jit: bool = True,
                 batch_cache=None, hints=None):
        self.catalog = catalog
        self._jit_cache = jit_cache if jit_cache is not None else {}
        self._use_jit = use_jit
        self._batch_cache = batch_cache
        self._hints = hints

    def _executor(self):
        from igloo_tpu.exec.executor import Executor
        return Executor(self._jit_cache, use_jit=self._use_jit,
                        batch_cache=self._batch_cache, hints=self._hints)

    def execute_to_arrow(self, plan: L.LogicalPlan, found) -> pa.Table:
        from igloo_tpu.catalog import MemTable
        from igloo_tpu.cluster.fragment import (
            decompose_aggregate, final_merge_plan, partial_aggregate_node,
        )
        path, agg, join, n_parts = found
        tracing.counter("grace.join")

        lparts = self._partition_side(join.left, join.left_keys, n_parts)
        rparts = self._partition_side(join.right, join.right_keys, n_parts)
        lbounds = self._union_bounds(join.left.schema, lparts)
        rbounds = self._union_bounds(join.right.schema, rparts)

        # per-partition plan: the join with its sides replaced by scans of
        # the partition tables, plus the path segment BELOW the aggregate
        below: list[L.LogicalPlan] = []
        if agg is not None:
            i = path.index(agg)
            below = path[i + 1:]
            partial_schema, partial_aggs, partial_names, final_spec = \
                decompose_aggregate(agg)

        partials: list[pa.Table] = []
        for p in range(n_parts):
            lt, rt = lparts[p], rparts[p]
            if lt.num_rows == 0 or rt.num_rows == 0:
                continue  # inner join: an empty side contributes nothing
            sub = self._rebuild_join(join, lt, rt, lbounds, rbounds)
            for node in reversed(below):
                sub = _rewire(node, sub)
            if agg is not None:
                sub = partial_aggregate_node(agg, sub, partial_schema,
                                             partial_aggs, partial_names)
            partials.append(self._executor().execute_to_arrow(sub))

        if agg is not None:
            if partials:
                merged_tbl = pa.concat_tables(partials)
            else:
                merged_tbl = partial_schema_empty(partial_schema)
            merged_scan = _mem_scan("__grace_partials", MemTable(merged_tbl),
                                    partial_schema)
            top = final_merge_plan(agg, merged_scan, final_spec)
            upper = path[: path.index(agg)]
        else:
            out_tbl = pa.concat_tables(partials) if partials else \
                partial_schema_empty(join.schema)
            top = _mem_scan("__grace_joined", MemTable(out_tbl), join.schema)
            upper = path
        for node in reversed(upper):
            top = _rewire(node, top)
        return self._executor().execute_to_arrow(top)

    # --- phase 1 ---

    def _partition_side(self, side: L.LogicalPlan, keys: list[E.Expr],
                        n_parts: int) -> list[pa.Table]:
        """Read the side provider-partition at a time through the device
        executor, hash its join keys host-side, split rows into buckets."""
        sc = next((n for n in L.walk_plan(side) if isinstance(n, L.Scan)), None)
        chunks: list[tuple] = [(None,)]
        if sc is not None and sc.provider is not None and sc.partition is None:
            try:
                np_ = sc.provider.num_partitions()
            except Exception:
                np_ = 1
            if np_ > 1:
                chunks = [(i,) for i in range(np_)]
        buckets: list[list[pa.Table]] = [[] for _ in range(n_parts)]
        key_names = [self._key_column_name(side, k) for k in keys]
        for chunk in chunks:
            sub = L.copy_plan(side)
            if chunk != (None,):
                sc2 = next(n for n in L.walk_plan(sub) if isinstance(n, L.Scan))
                sc2.partition = chunk
                tok = getattr(sc2.provider, "partition_token", None)
                if tok is not None:
                    try:
                        sc2.partition_token = tok()
                    except Exception:
                        pass
            tbl = self._executor().execute_to_arrow(sub)
            if tbl.num_rows == 0:
                continue
            h = np.zeros(tbl.num_rows, dtype=np.uint64)
            for name in key_names:
                col = tbl.column(name).combine_chunks()
                if pa.types.is_date32(col.type):
                    col = col.cast(pa.int32())  # date32 -> int64 is not a
                    # supported arrow cast; go through the day count
                vals = np.asarray(col.cast(pa.int64()).fill_null(0)) \
                    .astype(np.uint64)
                h = h * np.uint64(0x9E3779B97F4A7C15) + vals
                h ^= h >> np.uint64(29)
            pid = (h % np.uint64(n_parts)).astype(np.int64)
            for p in np.unique(pid):
                buckets[int(p)].append(
                    tbl.filter(pa.array(pid == p)))
        out = []
        for p in range(n_parts):
            out.append(pa.concat_tables(buckets[p]) if buckets[p]
                       else tbl_empty_like(side.schema))
        return out

    @staticmethod
    def _key_column_name(side: L.LogicalPlan, key: E.Expr) -> str:
        # find_grace_join admits only bare bound columns
        return side.schema.fields[key.index].name

    @staticmethod
    def _union_bounds(schema: T.Schema, tables: list) -> dict:
        """Per-column (lo, hi) over ALL partitions of one side, for integer-
        family columns. Attached to every partition MemTable (fixed_bounds,
        applied by Executor._exec_scan) so each partition presents IDENTICAL
        bounds to the executor: per-partition exact bounds would fork the
        jit/fused program caches P ways (bounds feed join-strategy constants
        and packed-key radices), while union bounds keep ONE compiled program
        per stage — and keep the packed-key single-sort path applicable inside
        every partition join/aggregate (hash partitioning spreads each key
        over its full global range anyway)."""
        import pyarrow.compute as pc
        out: dict = {}
        for f in schema:
            if not (f.dtype.is_integer or f.dtype.is_temporal):
                continue
            lo = hi = None
            for t in tables:
                if t.num_rows == 0:
                    continue
                # min_max consumes the ChunkedArray directly — no
                # combine_chunks/cast copies in the path that exists because
                # host memory is already tight; temporal scalars yield their
                # lane integers (days / microseconds) via .value
                mm = pc.min_max(t.column(f.name))
                if not mm["min"].is_valid:
                    continue
                if f.dtype.is_temporal:
                    mn, mx = mm["min"].value, mm["max"].value
                else:
                    mn, mx = mm["min"].as_py(), mm["max"].as_py()
                lo = mn if lo is None else min(lo, mn)
                hi = mx if hi is None else max(hi, mx)
            if lo is not None:
                out[f.name] = (int(lo), int(hi))
        return out

    # --- plan surgery ---

    @staticmethod
    def _rebuild_join(join: L.Join, lt: pa.Table, rt: pa.Table,
                      lbounds: Optional[dict] = None,
                      rbounds: Optional[dict] = None) -> L.Join:
        from igloo_tpu.catalog import MemTable
        j = L.copy_plan(join)
        lm, rm = MemTable(lt), MemTable(rt)
        if lbounds:
            lm.fixed_bounds = lbounds
        if rbounds:
            rm.fixed_bounds = rbounds
        j.left = _mem_scan("__grace_l", lm, join.left.schema)
        j.right = _mem_scan("__grace_r", rm, join.right.schema)
        return j


def _mem_scan(name: str, provider, schema: T.Schema) -> L.Scan:
    s = L.Scan(table=name, provider=provider)
    s.schema = schema
    return s


def _rewire(node: L.LogicalPlan, new_input: L.LogicalPlan) -> L.LogicalPlan:
    n = L.copy_plan(node)
    n.input = new_input
    return n


def tbl_empty_like(schema: T.Schema) -> pa.Table:
    from igloo_tpu.exec.batch import dtype_to_arrow
    arrays = [pa.array([], type=dtype_to_arrow(f.dtype)) for f in schema]
    return pa.Table.from_arrays(arrays, names=schema.names)


def partial_schema_empty(schema: T.Schema) -> pa.Table:
    return tbl_empty_like(schema)
