"""Out-of-core GRACE execution v2: multi-join partition pipelines on one device.

v1 (round-4) lifted the chunked executor's ceiling for exactly one shape — a
single bottom-level INNER equi-join under a decomposable aggregate.  SF10
Q3/Q5 stalled because their plans are *trees* of joins; anything past one join
fell back to monolithic execution.  v2 generalizes the planner and overlaps
host partitioning with device execution:

  plan analysis (find_grace_join): the plan below the usual upper path
      ([Limit] [Sort] [Project/Filter]* [Aggregate(decomposable)]) may be an
      arbitrary tree of INNER/SEMI/ANTI equi-joins.  Join keys that are bare
      columns trace down to (leaf, column) pairs; a union-find over the
      predicates yields KEY EQUIVALENCE CLASSES ("chains of shared key
      columns").  The partition scheme picks the best-scoring class (most
      over-budget bytes covered) whose assignment passes the anchor-analysis
      VALIDITY check (_scheme_valid): every leaf with a column in the class is
      CO-PARTITIONED by a shared hash of that column (equal values land in the
      same bucket on every side, so the union over buckets IS the join); the
      remaining leaves are REPLICATED (present in full in every partition).

  phase 1 (partition): each partitioned leaf is read provider-partition at a
      time through the device executor (filters/projections applied on
      device), and the surviving host Arrow rows split into P buckets by the
      key hash.  Integer/date/timestamp keys hash on their int64 lanes;
      dictionary-encoded STRING keys hash their dictionary bytes host-side
      (native/hash64.c via batch.hash64_bytes) and gather per row — equal
      strings hash equal across tables regardless of dictionary alignment.
      Replicated leaves execute once (streamed host-side when they are plain
      scan chains; routed through the chunked tier / recursive GRACE when
      they are complex subtrees).

  phase 2 (join, double-buffered): for p in 0..P the whole join tree runs on
      device with partitioned leaves replaced by bucket tables.  A background
      thread prepares partition p+1 — dictionary-encodes, codec-narrows and
      `device_put`s its buckets into prebuilt DeviceBatches — while partition
      p's jitted program runs, so HBM holds at most TWO partition pairs and
      the device never waits on host hashing/upload (IGLOO_GRACE_PIPELINE=0
      forces the serial loop for A/B).  All partitions of a leaf share one
      capacity (max bucket, pow2-rounded), one union dictionary per string
      column, union value bounds and union null-lane presence, so every
      partition keys the SAME compiled program per stage.

  recursion: when a partition's plan is still over budget (a replicated leaf
      bigger than the budget — its key was not in the chosen class), GRACE
      re-applies itself inside the partition on the next-best class, up to
      MAX_GRACE_DEPTH levels.

  merge: as v1 — decomposable aggregates run as per-partition partials merged
      once at the end; plain join trees concatenate host-side and the upper
      plan runs over the union.

The partition count is DERIVED from the budget (ceil(partitionable bytes /
budget)) and only clamped at MAX_GRACE_PARTITIONS, with a tracing counter
(`grace.partitions_clamped`) when the clamp re-opens a memory-bound gap.
Per-phase wall-clock rides the `grace.partition_ms` / `grace.join_ms` /
`grace.merge_ms` counters (surfaced by EXPLAIN ANALYZE).

The reference has no out-of-core story at all (its operators materialize
build sides in RAM HashMaps, crates/engine/src/operators/hash_join.rs:100-128).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import pyarrow as pa

from igloo_tpu import types as T
from igloo_tpu.exec import encoded
from igloo_tpu.plan import expr as E
from igloo_tpu.plan import logical as L
from igloo_tpu.sql.ast import JoinType
from igloo_tpu.utils import stats, tracing

# sanity clamp only — the real partition count is derived from the budget;
# past this the host-side bucket bookkeeping dominates and the clamp is
# reported via the grace.partitions_clamped counter instead of silently
# un-bounding memory (the old hard cap of 64 did exactly that)
MAX_GRACE_PARTITIONS = 1024
# recursive re-partitioning levels (level 0 = the outer GRACE execution)
MAX_GRACE_DEPTH = 3
# EXPLAIN ANALYZE records full operator subtrees for this many partitions;
# the rest contribute to the per-partition ROLLUP only (a 1024-partition
# query must not materialize 1024 stats subtrees)
DETAIL_PARTITIONS = 4

#: partitions that land as flight-recorder timeline spans (grace.partition /
#: grace.prefetch): enough to SEE the double-buffer overlap in Perfetto,
#: bounded so a 1024-partition query doesn't bloat its trace
_SPAN_PARTITIONS = 64

_INTERIOR_JOINS = (JoinType.INNER, JoinType.SEMI, JoinType.ANTI)

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


@dataclass
class GraceLeaf:
    """One leaf of the join tree: a subtree executable on its own."""
    node: L.LogicalPlan
    index: int
    nbytes: int                    # estimated lane bytes of its scans (0=unknown)
    over: bool                     # any single scan exceeds the budget
    kills: bool                    # empty leaf/bucket => empty partition result
    key_col: Optional[int] = None  # partition column in the leaf schema; None
    #                                => replicated into every partition


@dataclass
class GracePlan:
    """find_grace_join output: everything execute_to_arrow needs."""
    path: list                     # root chain down to (excluding) the join tree
    agg: Optional[L.Aggregate]
    root: L.LogicalPlan            # join-tree root
    leaves: list = field(default_factory=list)   # list[GraceLeaf]
    n_parts: int = 2


def _is_interior(node: L.LogicalPlan) -> bool:
    return isinstance(node, L.Join) and node.join_type in _INTERIOR_JOINS \
        and bool(node.left_keys)


def _key_eligible(key: E.Expr) -> bool:
    """Partition keys must be bare bound columns hashable host-side: the
    integer family (ints/dates/timestamps hash their int64 lanes) or strings
    (dictionary bytes hash through native hash64)."""
    if not isinstance(key, E.Column) or key.index is None or key.dtype is None:
        return False
    d = key.dtype
    return d.is_integer or d.is_temporal or d.is_string


def _collect_tree(root: L.LogicalPlan):
    """-> (joins, leaves) of the interior INNER/SEMI/ANTI equi-join tree.
    Filters above an interior join are transparent (kept in place by the
    per-partition rebuild); everything else is a leaf.  `kills` is False only
    for leaves under the right side of an ANTI join (an empty anti build side
    passes the probe side through, so such partitions must still run)."""
    joins: list[L.Join] = []
    leaves: list[GraceLeaf] = []

    def peel(n):
        while isinstance(n, L.Filter):
            n = n.input
        return n

    def walk(n, anti_right):
        j = peel(n)
        if _is_interior(j):
            joins.append(j)
            walk(j.left, anti_right)
            walk(j.right, anti_right or j.join_type is JoinType.ANTI)
        else:
            leaves.append(GraceLeaf(node=n, index=len(leaves), nbytes=0,
                                    over=False, kills=not anti_right))

    walk(root, False)
    return joins, leaves


def _trace_leaf_col(node: L.LogicalPlan, idx: int, leaf_ids: dict):
    """Resolve a bound column index against `node`'s output down to a
    (leaf id, leaf column index) pair; None when the column crosses a
    non-transparent node (e.g. a Project between joins)."""
    while True:
        if id(node) in leaf_ids:
            return (id(node), idx)
        if isinstance(node, L.Filter):
            node = node.input
            continue
        if isinstance(node, L.Join):
            if node.join_type in (JoinType.SEMI, JoinType.ANTI):
                node = node.left   # output schema = left side
                continue
            nl = len(node.left.schema)
            if idx < nl:
                node = node.left
            else:
                idx -= nl
                node = node.right
            continue
        return None


def find_grace_join(plan: L.LogicalPlan, budget_bytes: int):
    """Locate a GRACE-v2-eligible over-budget join tree. Returns a GracePlan
    or None when the plan does not qualify (caller takes the normal path)."""
    from igloo_tpu.cluster.fragment import _DECOMPOSABLE
    from igloo_tpu.exec.chunked import estimated_lane_bytes
    path: list[L.LogicalPlan] = []
    node = plan
    agg: Optional[L.Aggregate] = None
    while True:
        if isinstance(node, (L.Limit, L.Sort, L.Project, L.Filter)):
            path.append(node)
            node = node.input
        elif isinstance(node, L.Aggregate) and agg is None and \
                not any(a.distinct for a in node.aggs) and \
                all(a.func in _DECOMPOSABLE for a in node.aggs):
            agg = node
            path.append(node)
            node = node.input
        else:
            break
    if not _is_interior(node):
        return None
    joins, leaves = _collect_tree(node)

    over_any = False
    for leaf in leaves:
        total = 0
        for sc in L.walk_plan(leaf.node):
            if isinstance(sc, L.Scan) and sc.provider is not None:
                b = estimated_lane_bytes(sc.provider)
                if b is not None:
                    total += b
                    if b > budget_bytes:
                        leaf.over = True
                        over_any = True
        leaf.nbytes = total
    if not over_any:
        return None

    # key equivalence classes over (leaf, column) via union-find
    leaf_ids = {id(leaf.node): leaf for leaf in leaves}
    parent: dict = {}

    def find(x):
        while parent.setdefault(x, x) != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a, b):
        parent[find(a)] = find(b)

    for j in joins:
        for lk, rk in zip(j.left_keys, j.right_keys):
            if not (_key_eligible(lk) and _key_eligible(rk)):
                continue
            a = _trace_leaf_col(j.left, lk.index, leaf_ids)
            b = _trace_leaf_col(j.right, rk.index, leaf_ids)
            if a is not None and b is not None:
                union(a, b)
    classes: dict = {}
    for x in list(parent):
        classes.setdefault(find(x), []).append(x)

    # partition-scheme selection: classes ranked by over-budget bytes covered
    # (ties: partitionable bytes overall); the best class whose assignment
    # passes the co-location VALIDITY check (anchor analysis below) wins
    cands = []
    for members in classes.values():
        cols: dict[int, int] = {}   # leaf id -> first class column
        for lid, col in sorted(members, key=lambda m: m[1]):
            cols.setdefault(lid, col)
        over_b = sum(leaf_ids[lid].nbytes for lid in cols
                     if leaf_ids[lid].over)
        part_b = sum(leaf_ids[lid].nbytes for lid in cols)
        if over_b > 0:
            cands.append(((over_b, part_b), cols))
    cands.sort(key=lambda c: c[0], reverse=True)
    best = next(((score, cols) for score, cols in cands
                 if _scheme_valid(node, leaf_ids, cols)), None)
    if best is None:
        return None
    (_, part_bytes), cols = best
    for lid, col in cols.items():
        leaf_ids[lid].key_col = col

    need = max(2, -(-part_bytes // max(budget_bytes, 1)))
    if need > MAX_GRACE_PARTITIONS:
        tracing.counter("grace.partitions_clamped")
        tracing.log.warning(
            "grace: %d partitions needed to bound memory, clamped to %d "
            "(per-partition working set will exceed the %d-byte budget)",
            need, MAX_GRACE_PARTITIONS, budget_bytes)
        need = MAX_GRACE_PARTITIONS
    return GracePlan(path=path, agg=agg, root=node, leaves=leaves,
                     n_parts=int(need))


def _scheme_valid(root: L.LogicalPlan, leaf_ids: dict,
                  part_cols: dict) -> bool:
    """Compositional co-location check for a candidate partition assignment.

    Per subtree we compute (valid, free, anchors): `free` = the subtree has no
    partitioned leaf (its tuples appear in EVERY partition); otherwise
    `anchors` = output columns whose value v satisfies "tuple t of this
    subtree exists in partition p iff p == hash(v) % P".  Leaves partitioned
    by k anchor {k}; inner joins propagate anchors and close them over their
    equi pairs, requiring a linking pair when BOTH sides are anchored (else
    joined rows could land in different buckets and the per-partition union
    would lose tuples).  SEMI/ANTI scope the analysis: witnesses live only in
    the bucket of the join key, so a partitioned build side demands a key
    pair whose probe column is anchored (ANTI additionally forbids a free
    probe side — a replicated probe row would spuriously survive in every
    bucket its witnesses are NOT in).  A False here rejects the class; the
    planner falls back to the next-best class or the normal path."""
    def pairs_of(j: L.Join):
        out = []
        for lk, rk in zip(j.left_keys, j.right_keys):
            if isinstance(lk, E.Column) and lk.index is not None and \
                    isinstance(rk, E.Column) and rk.index is not None:
                out.append((lk.index, rk.index))
        return out

    def rec(nd):
        if id(nd) in leaf_ids:
            col = part_cols.get(id(nd))
            if col is None:
                return True, True, set()
            return True, False, {col}
        if isinstance(nd, L.Filter):
            return rec(nd.input)
        j = nd
        vl, fl, al = rec(j.left)
        vr, fr, ar = rec(j.right)
        if not (vl and vr):
            return False, True, set()
        pairs = pairs_of(j)
        if j.join_type is JoinType.INNER:
            if not fl and not fr and \
                    not any(li in al and ri in ar for li, ri in pairs):
                return False, True, set()
            nl = len(j.left.schema)
            comb = set(al if not fl else ()) | \
                {nl + c for c in (ar if not fr else ())}
            changed = True
            while changed:
                changed = False
                for li, ri in pairs:
                    if li in comb and nl + ri not in comb:
                        comb.add(nl + ri)
                        changed = True
                    if nl + ri in comb and li not in comb:
                        comb.add(li)
                        changed = True
            return True, fl and fr, comb
        # SEMI / ANTI: output = probe (left) side only
        if fr:
            return True, fl, al
        links = {li for li, ri in pairs if ri in ar}
        if not links:
            return False, True, set()
        if not fl:
            if not (links & al):
                return False, True, set()
            return True, False, al
        if j.join_type is JoinType.ANTI:
            # free probe + partitioned build: a probe row would survive in
            # every bucket except its witnesses' — unsound
            return False, True, set()
        # SEMI with free probe: a probe row's witnesses all live in
        # hash(link key), so it is emitted exactly once, anchored by that key
        return True, False, set(links)

    valid, _, _ = rec(root)
    return valid


# --- host-side partition hashing -------------------------------------------


def _hash_rows(tbl: pa.Table, name: str) -> np.ndarray:
    """uint64 hash lane of one key column, host-side. Strings hash their
    dictionary bytes once (native hash64.c fast path) and gather per row, so
    the per-row cost is one int32 take regardless of string length."""
    import pyarrow.compute as pc
    from igloo_tpu.exec.batch import hash64_bytes
    col = tbl.column(name)
    col = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    typ = col.type
    if pa.types.is_dictionary(typ) or pa.types.is_string(typ) or \
            pa.types.is_large_string(typ):
        if not pa.types.is_dictionary(typ):
            col = col.dictionary_encode()
        dvals = np.asarray(col.dictionary.to_numpy(zero_copy_only=False),
                           dtype=object)
        ids = np.asarray(pc.fill_null(col.indices, 0)).astype(np.int64)
        if len(dvals) == 0:
            vals = np.zeros(len(col), dtype=np.uint64)
        else:
            vals = hash64_bytes(dvals, seed=0)[ids]
    else:
        if pa.types.is_date32(typ):
            col = col.cast(pa.int32())  # date32 -> int64 is not a supported
            # arrow cast; go through the day count
        vals = np.asarray(col.cast(pa.int64()).fill_null(0)).astype(np.uint64)
    h = vals * _GOLDEN
    return h ^ (h >> np.uint64(29))


def _split_by_hash(tbl: pa.Table, name: str, n_parts: int,
                   buckets: list) -> None:
    """Append `tbl`'s rows to `buckets` by key hash: ONE stable argsort of the
    partition ids + boundary slices instead of P full-table filters."""
    pid = (_hash_rows(tbl, name) % np.uint64(n_parts)).astype(np.int64)
    order = np.argsort(pid, kind="stable")
    sorted_tbl = tbl.take(order)
    counts = np.bincount(pid, minlength=n_parts)
    off = 0
    for p in range(n_parts):
        c = int(counts[p])
        if c:
            buckets[p].append(sorted_tbl.slice(off, c))
        off += c


# unique snapshot tokens for grace-created providers: the scan cache's
# fallback snapshot used to be a bare id(provider), and the partition loop
# allocates/frees one provider per partition — CPython happily REUSES a
# freed provider's id, which made the cache serve partition p-1's columns as
# partition p's. Tokens come from a monotonic counter; the PREFETCH thread
# builds _PartitionTables (each drawing a token) concurrently with
# main-thread provider stamping, so the counter bump is lock-guarded instead
# of leaning on itertools.count()'s accidental GIL atomicity.
#
# lock discipline (checked by igloo-lint lock-discipline):
_GUARDED_BY = {"_snap_lock": ("_snap_ids",)}
_snap_lock = threading.Lock()
_snap_ids = 0


def _fresh_snapshot() -> str:
    global _snap_ids
    with _snap_lock:
        _snap_ids += 1
        return f"__grace_snap_{_snap_ids}"


def _stamp_snapshot(provider) -> object:
    tok = _fresh_snapshot()
    provider.snapshot = lambda _tok=tok: _tok
    return provider


class _PartitionTable:
    """Bucket provider: a MemTable that may carry a prebuilt DeviceBatch
    (uploaded by the prefetch thread; Executor._scan_batch returns it
    directly) and union value bounds pinned across all partitions."""

    stable_row_order = True

    def __init__(self, table: pa.Table):
        from igloo_tpu.exec.batch import schema_from_arrow
        self._table = table
        self._schema = schema_from_arrow(table.schema)
        self.prebuilt_batch = None
        self.fixed_bounds: Optional[dict] = None
        self._snap = _fresh_snapshot()

    def snapshot(self) -> str:
        return self._snap

    def __deepcopy__(self, memo):
        return self

    def schema(self):
        return self._schema

    def read(self, projection=None, filters=None) -> pa.Table:
        t = self._table
        if projection is not None:
            t = t.select(projection)
        return t

    def num_partitions(self) -> int:
        return 1

    def read_partition(self, index, projection=None, filters=None):
        return self.read(projection=projection, filters=filters)

    def estimated_bytes(self) -> int:
        return self._table.nbytes


class GraceJoinExecutor:
    """Executes a qualifying plan partition at a time (see module doc)."""

    def __init__(self, catalog, jit_cache=None, use_jit: bool = True,
                 batch_cache=None, hints=None,
                 budget_bytes: int = 2 << 30):
        self.catalog = catalog
        self._jit_cache = jit_cache if jit_cache is not None else {}
        self._use_jit = use_jit
        self._batch_cache = batch_cache
        self._hints = hints
        self.budget_bytes = budget_bytes
        self._exec = None  # ONE Executor reused across partitions and phases

    def _executor(self):
        if self._exec is None:
            from igloo_tpu.exec.executor import Executor
            self._exec = Executor(self._jit_cache, use_jit=self._use_jit,
                                  batch_cache=self._batch_cache,
                                  hints=self._hints)
        return self._exec

    # --- entry --------------------------------------------------------------

    def execute_to_arrow(self, plan: L.LogicalPlan, found: GracePlan,
                         depth: int = 0) -> pa.Table:
        with stats.op("GraceJoin", partitions=found.n_parts,
                      depth=depth) as gnode:
            return self._execute(plan, found, depth, gnode)

    def _execute(self, plan: L.LogicalPlan, found: GracePlan,
                 depth: int, gnode) -> pa.Table:
        from igloo_tpu.catalog import MemTable
        from igloo_tpu.cluster.fragment import (
            decompose_aggregate, final_merge_plan, partial_aggregate_node,
        )
        gp = found
        tracing.counter("grace.join")
        tracing.counter("grace.partitions", gp.n_parts)
        if depth:
            tracing.counter("grace.recursive")
        used_names: list[str] = []
        try:
            # --- phase 1: partition / replicate the leaves -------------------
            t0 = time.perf_counter()
            parted: dict[int, list[pa.Table]] = {}
            rep_prov: dict[int, object] = {}
            with stats.op("GracePhase(partition)"):
                for leaf in gp.leaves:
                    if leaf.key_col is not None:
                        parted[leaf.index] = self._partition_leaf(
                            leaf, gp.n_parts, depth)
                        used_names.append(f"__grace_p{leaf.index}")
                    else:
                        tbl = self._leaf_to_arrow(leaf.node, depth)
                        # sliceable provider partitions so a RECURSIVE grace
                        # level can stream this table instead of
                        # device-reading it whole
                        parts = max(
                            1, -(-tbl.nbytes // max(self.budget_bytes, 1)))
                        rep_prov[leaf.index] = _stamp_snapshot(
                            MemTable(tbl, partitions=parts))
                        used_names.append(f"__grace_rep{leaf.index}")
            tracing.counter("grace.partition_ms",
                            int(1000 * (time.perf_counter() - t0)))

            # a replicated over-budget leaf means this level cannot bound its
            # memory — partitions re-enter GRACE (recursion), so skip the
            # prebuilt device uploads their plans would never use
            recursive_mode = depth + 1 < MAX_GRACE_DEPTH and any(
                leaf.key_col is None and leaf.over for leaf in gp.leaves)

            # recursive mode skips the prebuilt uploads, so only the union
            # bounds (consumed via fixed_bounds) are worth computing — the
            # union dictionaries / null scans / shared capacity would be
            # discarded by prepare()
            if recursive_mode:
                meta = {i: (self._union_bounds(
                            self._leaf_of(gp, i).node.schema, parted[i]),
                            {}, 0, set())
                        for i in parted}
            else:
                meta = {i: self._leaf_meta(self._leaf_of(gp, i), parted[i])
                        for i in parted}

            # partitions that cannot produce rows (an empty co-partitioned
            # bucket on any inner/semi-reachable leaf) are skipped outright
            killing = [leaf.index for leaf in gp.leaves
                       if leaf.key_col is not None and leaf.kills]
            run_ps = [p for p in range(gp.n_parts)
                      if all(parted[i][p].num_rows > 0 for i in killing)]
            if any(leaf.key_col is None and leaf.kills and
                   rep_prov[leaf.index].read().num_rows == 0
                   for leaf in gp.leaves):
                run_ps = []

            below: list[L.LogicalPlan] = []
            if gp.agg is not None:
                i = gp.path.index(gp.agg)
                below = gp.path[i + 1:]
                partial_schema, partial_aggs, partial_names, final_spec = \
                    decompose_aggregate(gp.agg)

            def prepare(p: int) -> dict:
                provs = {}
                for i in parted:
                    # widen THIS bucket only (the others stay in carrier
                    # form); from_arrow then re-narrows at the device edge
                    tbl = encoded.decode_table(parted[i][p])
                    prov = _PartitionTable(tbl)
                    bounds, udicts, cap, nullf = meta[i]
                    prov.fixed_bounds = bounds
                    if not recursive_mode:
                        from igloo_tpu.exec.batch import from_arrow
                        prov.prebuilt_batch = from_arrow(
                            tbl,
                            schema=self._leaf_of(gp, i).node.schema,
                            capacity=cap, dictionaries=udicts or None,
                            null_fields=nullf or None)
                    provs[i] = prov
                return provs

            def build_sub(provs: dict) -> L.LogicalPlan:
                repl = {}
                for leaf in gp.leaves:
                    prov = provs[leaf.index] if leaf.key_col is not None \
                        else rep_prov[leaf.index]
                    name = (f"__grace_p{leaf.index}"
                            if leaf.key_col is not None
                            else f"__grace_rep{leaf.index}")
                    repl[id(leaf.node)] = _mem_scan(name, prov,
                                                    leaf.node.schema)
                sub = _replace_leaves(gp.root, repl)
                for nd in reversed(below):
                    sub = _rewire(nd, sub)
                if gp.agg is not None:
                    sub = partial_aggregate_node(gp.agg, sub, partial_schema,
                                                 partial_aggs, partial_names)
                return sub

            # --- phase 2: the (double-buffered) partition loop ---------------
            t0 = time.perf_counter()
            pipeline = os.environ.get("IGLOO_GRACE_PIPELINE", "1") != "0" \
                and not recursive_mode and len(run_ps) > 1
            partials: list[pa.Table] = []
            part_rows: list[int] = []
            part_wall: list[float] = []

            def run_partition(k: int, p: int, provs: dict) -> None:
                """One partition's plan on device; rows (host Arrow — free)
                and wall feed the per-partition rollup. The first few
                partitions keep full operator subtrees under EXPLAIN
                ANALYZE; the rest are recorded quiet (rollup only). The
                first _SPAN_PARTITIONS land as `grace.partition` timeline
                spans — on the Perfetto view they visibly overlap the
                prefetch thread's `grace.prefetch` spans, which is the
                double-buffer's win made observable."""
                tp = time.perf_counter()
                keep = stats.detail_active() and k < DETAIL_PARTITIONS
                cm = stats.op(f"Partition[{p}]") if keep else stats.quiet()
                span_cm = tracing.span("grace.partition", partition=p) \
                    if k < _SPAN_PARTITIONS else contextlib.nullcontext()
                with span_cm, cm:
                    tbl = self._leaf_routed(build_sub(provs), depth)
                    if keep:
                        stats.set_rows(tbl.num_rows)
                partials.append(tbl)
                part_rows.append(tbl.num_rows)
                part_wall.append(time.perf_counter() - tp)

            with stats.op("GracePhase(join)"):
                if pipeline:
                    tracing.counter("grace.pipeline")
                    from concurrent.futures import ThreadPoolExecutor
                    # the prefetch thread adopts this query's stats context
                    # so its uploads/counters land in the right deltas
                    sctx = stats.capture()

                    def prepare_traced(k: int, p: int) -> dict:
                        # the adopted trace context puts the prefetch span
                        # in the SAME query trace as the compute spans it
                        # overlaps. Gated on the execution ORDINAL k, same
                        # as grace.partition — skipped-empty-partition runs
                        # have sparse partition IDs, and gating the two
                        # halves differently would trace compute without
                        # its overlapping prefetch
                        with stats.adopt(sctx):
                            span_cm = tracing.span("grace.prefetch",
                                                   partition=p) \
                                if k < _SPAN_PARTITIONS \
                                else contextlib.nullcontext()
                            with span_cm:
                                return prepare(p)

                    with ThreadPoolExecutor(max_workers=1) as pool:
                        fut = pool.submit(prepare_traced, 0, run_ps[0])
                        for k, p in enumerate(run_ps):
                            provs = fut.result()
                            if k + 1 < len(run_ps):
                                fut = pool.submit(prepare_traced, k + 1,
                                                  run_ps[k + 1])
                            run_partition(k, p, provs)
                else:
                    for k, p in enumerate(run_ps):
                        run_partition(k, p, prepare(p))
            tracing.counter("grace.join_ms",
                            int(1000 * (time.perf_counter() - t0)))
            if gnode is not None:
                gnode.attrs.update(
                    partitions_run=len(run_ps),
                    partitions_skipped=gp.n_parts - len(run_ps),
                    pipeline=bool(pipeline))
                if part_rows:
                    gnode.attrs["partition_rows"] = (
                        f"min={min(part_rows)}/"
                        f"avg={sum(part_rows) // len(part_rows)}/"
                        f"max={max(part_rows)}")
                    gnode.attrs["partition_ms"] = (
                        f"min={1e3 * min(part_wall):.1f}/"
                        f"avg={1e3 * sum(part_wall) / len(part_wall):.1f}/"
                        f"max={1e3 * max(part_wall):.1f}")

            # --- merge -------------------------------------------------------
            t0 = time.perf_counter()
            with stats.op("GracePhase(merge)"):
                if gp.agg is not None:
                    merged_tbl = pa.concat_tables(partials) if partials else \
                        partial_schema_empty(partial_schema)
                    merged_scan = _mem_scan(
                        "__grace_partials",
                        _stamp_snapshot(MemTable(merged_tbl)),
                        partial_schema)
                    top = final_merge_plan(gp.agg, merged_scan, final_spec)
                    upper = gp.path[: gp.path.index(gp.agg)]
                    used_names.append("__grace_partials")
                else:
                    out_tbl = pa.concat_tables(partials) if partials else \
                        tbl_empty_like(gp.root.schema)
                    top = _mem_scan("__grace_joined",
                                    _stamp_snapshot(MemTable(out_tbl)),
                                    gp.root.schema)
                    upper = gp.path
                    used_names.append("__grace_joined")
                for nd in reversed(upper):
                    top = _rewire(nd, top)
                out = self._executor().execute_to_arrow(top)
                stats.set_rows(out.num_rows)
            tracing.counter("grace.merge_ms",
                            int(1000 * (time.perf_counter() - t0)))
            return out
        finally:
            # free the HBM the loop's same-name scan-cache slots still pin
            if self._batch_cache is not None:
                for name in used_names:
                    self._batch_cache.invalidate_table(name.lower())

    @staticmethod
    def _leaf_of(gp: GracePlan, index: int) -> GraceLeaf:
        return gp.leaves[index]

    # --- phase 1 -------------------------------------------------------------

    def _partition_leaf(self, leaf: GraceLeaf, n_parts: int,
                        depth: int) -> list[pa.Table]:
        """Stream the leaf through the device executor and split its output
        rows into co-partition buckets by the class-key hash."""
        key_name = leaf.node.schema.fields[leaf.key_col].name
        buckets: list[list[pa.Table]] = [[] for _ in range(n_parts)]
        for tbl in self._leaf_chunks(leaf.node, depth):
            if tbl.num_rows:
                _split_by_hash(tbl, key_name, n_parts, buckets)
        # partition buffers are the long-lived host state of the whole loop:
        # hold them in carrier form (exec/encoded.py; numerics only — string
        # buckets must stay plain so _union_dicts sees the raw values).
        # prepare() widens one bucket at a time, right before upload.
        # Per-bucket specs are safe here: buckets are never co-hashed again
        out = [encoded.encode_table(
                   pa.concat_tables(b) if b else
                   tbl_empty_like(leaf.node.schema))
               for b in buckets]
        tracing.counter("grace.partition_bytes", sum(t.nbytes for t in out))
        return out

    def _leaf_chunks(self, node: L.LogicalPlan, depth: int):
        """Yield the leaf's output host-side without ever materializing more
        than one provider partition on device: plain scan chains stride the
        provider's partitions; complex subtrees route through the chunked
        tier / recursive GRACE / plain executor."""
        from igloo_tpu.cluster.fragment import _subtree_scan
        sc = _subtree_scan(node)
        np_ = 1
        if sc is not None and sc.provider is not None and sc.partition is None:
            try:
                np_ = sc.provider.num_partitions()
            except Exception:
                np_ = 1
        if sc is not None and sc.provider is not None and \
                sc.partition is None and np_ > 1:
            from igloo_tpu.cluster.fragment import _with_partition
            from igloo_tpu.storage import prefetch as _prefetch
            # feed the partition stride through the storage prefetcher: the
            # reader thread decodes row group i+1 while partition i's plan
            # runs on device (docs/storage.md#prefetch) — the cold-scan half
            # of the double-buffer this loop feeds
            items = [(sc.provider, i, sc.projection, sc.pushed_filters)
                     for i in range(np_)]
            with _prefetch.scan_prefetch(items):
                for i in range(np_):
                    yield self._executor().execute_to_arrow(
                        _with_partition(node, (i,)))
            return
        yield self._leaf_routed(node, depth)

    def _leaf_routed(self, node: L.LogicalPlan, depth: int) -> pa.Table:
        """Execute a whole subtree (a complex leaf, or one partition's plan)
        with the engine's memory ladder: chunked tier for decomposable
        aggregates, recursive GRACE when the subtree is still over budget
        (e.g. a replicated leaf bigger than the budget), plain executor
        otherwise."""
        from igloo_tpu.exec.chunked import LocalChunkExecutor, chunk_count
        chunks = chunk_count(node, self.budget_bytes)
        if chunks:
            return LocalChunkExecutor(
                self.catalog, self._jit_cache, use_jit=self._use_jit,
                batch_cache=self._batch_cache,
                chunks=chunks).execute_to_arrow(node)
        if depth + 1 < MAX_GRACE_DEPTH:
            found = find_grace_join(node, self.budget_bytes)
            if found is not None:
                return self.execute_to_arrow(node, found, depth + 1)
        return self._executor().execute_to_arrow(node)

    def _leaf_to_arrow(self, node: L.LogicalPlan, depth: int) -> pa.Table:
        ts = list(self._leaf_chunks(node, depth))
        return ts[0] if len(ts) == 1 else pa.concat_tables(ts)

    # --- shared per-leaf metadata (one compiled program per stage) -----------

    def _leaf_meta(self, leaf: GraceLeaf, tables: list):
        """(union bounds, union dictionaries, shared capacity, union null
        columns) over ALL buckets of one leaf: every partition presents
        IDENTICAL static metadata to the executor, keeping ONE compiled
        program per stage (per-bucket exact values would fork the jit/fused
        caches P ways — bounds feed join-strategy constants and packed-key
        radices, dictionary/capacity/null-lane shapes feed the pool and batch
        prototypes)."""
        schema = leaf.node.schema
        bounds = self._union_bounds(schema, tables)
        udicts = _union_dicts(schema, tables)
        from igloo_tpu.exec.batch import round_capacity
        cap = round_capacity(max((t.num_rows for t in tables), default=1) or 1)
        nullf = {f.name for f in schema
                 if any(t.num_rows and t.column(f.name).null_count
                        for t in tables)}
        return bounds, udicts, cap, nullf

    @staticmethod
    def _union_bounds(schema: T.Schema, tables: list) -> dict:
        """Per-column (lo, hi) over ALL partitions of one leaf, for integer-
        family columns (a superset range is always safe for the consumers:
        direct-join table sizing, packed-key radices — and hash partitioning
        spreads each key over its full global range anyway)."""
        out: dict = {}
        for f in schema:
            if not (f.dtype.is_integer or f.dtype.is_temporal):
                continue
            lo = hi = None
            for t in tables:
                # min_max consumes the ChunkedArray directly — no
                # combine_chunks/cast copies in the path that exists because
                # host memory is already tight. column_min_max reads LOGICAL
                # bounds off encoded buckets without widening them (the
                # carrier min/max plus the field's recorded offset) and
                # yields temporal lane integers (days / microseconds)
                mm = encoded.column_min_max(t, f.name)
                if mm is None:
                    continue
                mn, mx = mm
                lo = mn if lo is None else min(lo, mn)
                hi = mx if hi is None else max(hi, mx)
            if lo is not None:
                out[f.name] = (int(lo), int(hi))
        return out


def _union_dicts(schema: T.Schema, tables: list) -> dict:
    """One shared (sorted) dictionary per string column across ALL buckets of
    a leaf, so every partition's ids gather through identically-shaped hash
    lanes and the compile caches see one dictionary fingerprint."""
    from igloo_tpu.exec.batch import DictInfo
    out: dict = {}
    for f in schema:
        if not f.dtype.is_string:
            continue
        vals: set = set()
        for t in tables:
            if t.num_rows == 0:
                continue
            c = t.column(f.name)
            c = c.combine_chunks() if isinstance(c, pa.ChunkedArray) else c
            if not pa.types.is_dictionary(c.type):
                c = c.dictionary_encode()
            dv = c.dictionary.to_numpy(zero_copy_only=False)
            vals.update(v for v in dv if v is not None)
        out[f.name] = DictInfo.from_values(
            np.asarray(sorted(vals), dtype=object))
    return out


# --- plan surgery -----------------------------------------------------------


def _replace_leaves(node: L.LogicalPlan, repl: dict) -> L.LogicalPlan:
    """Shallow-rebuild the join tree with leaves swapped for bucket scans.
    Interior joins and transparent filters are copy.copy'd (keys/predicates
    stay SHARED across partitions, so scalar-subquery memos resolve once)."""
    import copy as _copy
    r = repl.get(id(node))
    if r is not None:
        return r
    n = _copy.copy(node)
    if isinstance(n, L.Filter):
        n.input = _replace_leaves(node.input, repl)
        return n
    assert isinstance(n, L.Join)
    n.left = _replace_leaves(node.left, repl)
    n.right = _replace_leaves(node.right, repl)
    return n


def _mem_scan(name: str, provider, schema: T.Schema) -> L.Scan:
    s = L.Scan(table=name, provider=provider)
    s.schema = schema
    return s


def _rewire(node: L.LogicalPlan, new_input: L.LogicalPlan) -> L.LogicalPlan:
    n = L.copy_plan(node)
    n.input = new_input
    return n


def tbl_empty_like(schema: T.Schema) -> pa.Table:
    from igloo_tpu.exec.batch import dtype_to_arrow
    arrays = [pa.array([], type=dtype_to_arrow(f.dtype)) for f in schema]
    return pa.Table.from_arrays(arrays, names=schema.names)


def partial_schema_empty(schema: T.Schema) -> pa.Table:
    return tbl_empty_like(schema)
