"""Pallas TPU kernels for the join/agg hot loop (ROADMAP item 5).

The engine is sort-based end to end: every equi-join pays `_probe_bounds`'s
full (m+n)-lane argsort and every sort-tier GROUP BY pays a multi-lane lex
sort before segment reduction. These kernels attack the three hottest
primitives with the ragged-output idiom from "Ragged Paged Attention"
(PAPERS.md) — a grid over fixed-size blocks with per-block valid counts and
bounded per-row emission windows, overflow reported as a deferred flag the
executor repairs with an exact sort-path re-run:

- ``hash_probe_bounds``: the build side's key-hash lane is sorted ONCE
  (m lanes — the argsort the caller already pays for ``perm_r``) and
  bucketed by the hash's top bits, so bucket order == sort order and every
  bucket is a contiguous run. The probe kernel then scans a bounded
  ``window`` of its bucket per probe row — equality-only compares, since
  equal hashes are contiguous in sorted order — replacing the combined
  (m+n)-lane stable sort of ``join._probe_bounds`` with one bandwidth-bound
  pass over the probe side. A run that may extend past the window raises
  the overflow flag (exact semantics in ``_probe_kernel``).

- ``hash_segagg``: one-pass blocked hash aggregation over an EXACT integer
  group-key lane (the ``kernels.pack_key_lane`` packed lane — injective, so
  slot-key equality IS group equality, no verify pass). A ``ways``-slot
  bucket per hash gives bounded collision resolution; every aggregate
  accumulates into the VMEM-resident table in the same pass over the input,
  replacing the ``lex_argsort -> group_segments -> seg_*`` chain with one
  read of the input. Bucket exhaustion (more distinct keys than slots)
  raises the overflow flag.

- ``fused_gather``: one kernel materializing every output lane of a batch
  gather (``kernels.gather_batch`` / ``apply_perm``) instead of one XLA
  gather per lane — the index block is read once and all columns gather
  against it.

Block shapes and table sizes are chosen by ``exec/dispatch.py`` from the
canonical capacity families (exec/capacity.py), so kernel programs are keyed
by the same small shape family as the rest of the engine and the compile
cache converges. ``interpret=True`` runs the kernels through the Pallas
interpreter on CPU — that is how tier-1 asserts equivalence without
hardware (``IGLOO_TPU_PALLAS=interpret``).

Block shapes and tables can also come from the per-shape tuning table
(``exec/autotune.py``, docs/kernels.md#autotuner) — tuned values still pass
through the same planner eligibility clamps.

Access policy: ``exec/dispatch.py`` and ``exec/autotune.py`` (the candidate
benchmark harness) are the ONLY legal callers (igloo-lint ``pallas-dispatch``
rule) — the flag and the fallback ladder must not be bypassable.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from igloo_tpu.exec.dispatch import EMPTY_KEY

# "no position yet" sentinel in the min/max winner-position tables
_BIG_POS = np.int32(1 << 30)


def _bucket_of(h: jax.Array, bits: int) -> jax.Array:
    """Bucket id of an int64 hash: its top `bits` bits in SIGN-BIASED
    (unsigned) order, so ascending bucket id == ascending int64 sort order
    and each bucket is a contiguous run of the sorted hash lane."""
    u = h.astype(jnp.uint64) ^ jnp.uint64(1 << 63)
    return jax.lax.shift_right_logical(
        u, np.uint64(64 - bits)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# 1. hash probe with ragged output
# ---------------------------------------------------------------------------

def _probe_kernel(starts_ref, hash_ref, probe_ref, lo_ref, cnt_ref, ovf_ref,
                  *, bits: int, window: int, bsteps: int):
    """One probe block: per row, an in-bucket binary search finds the
    probe's insertion point (`bsteps` static iterations cover the longest
    possible bucket), then a bounded `window` scan counts the equal-hash
    run — contiguous because the lane is sorted, so equality compares
    suffice. `lower` equals the sort path's left insertion position for
    EVERY row (matched or not).

    Overflow is exact: it fires only when the probe's OWN run extends past
    the window (one lookahead slot distinguishes a run of exactly `window`
    from a truncated one). Long runs of other keys in the same bucket —
    including the dead-row MAX-sentinel run and the displaced-NULL runs,
    which share one hash value each — never flag.

    Hashes compare with the LOW BIT DROPPED (& -2), matching
    ``join._probe_bounds``'s 63-bit semantics (its low bit carries the side
    tag): the kernel's bounds are then bit-identical to the sort path's —
    same candidate sets, totals, and match capacities — and the extra
    candidates a dropped bit admits die in exact verification like they
    always have. Masked-equal values differ only in bit 0, so their run is
    still contiguous in the full-value sort order."""
    mask = np.int64(-2)
    h = probe_ref[...]
    hm = h & mask
    b = _bucket_of(h, bits)
    starts = starts_ref[...]
    s = jnp.take(starts, b)
    e = jnp.take(starts, b + 1)
    table = hash_ref[...]
    m = table.shape[0]
    lo, hi = s, e
    for _ in range(bsteps):
        cond = lo < hi
        mid = (lo + hi) >> 1
        less = (jnp.take(table, jnp.clip(mid, 0, m - 1)) & mask) < hm
        lo = jnp.where(cond & less, mid + 1, lo)
        hi = jnp.where(cond & ~less, mid, hi)
    blk = h.shape[0]
    cnt = jnp.zeros((blk,), jnp.int32)
    eq_last = jnp.zeros((blk,), bool)
    for off in range(window):
        pos = lo + off
        eq = (pos < e) & \
            ((jnp.take(table, jnp.clip(pos, 0, m - 1)) & mask) == hm)
        cnt = cnt + eq.astype(jnp.int32)
        if off == window - 1:
            eq_last = eq
    look = lo + window
    ovf = eq_last & (look < e) & \
        ((jnp.take(table, jnp.clip(look, 0, m - 1)) & mask) == hm)
    lo_ref[...] = lo
    cnt_ref[...] = cnt

    @pl.when(pl.program_id(0) == 0)
    def _():
        ovf_ref[...] = jnp.zeros_like(ovf_ref)

    ovf_ref[...] = ovf_ref[...] | jnp.any(ovf)


def hash_probe_bounds(sorted_hash: jax.Array, probe_hash: jax.Array,
                      nbuckets: int, window: int, block: int,
                      interpret: bool):
    """(lower, upper, overflow) of each probe hash's equal-key run in the
    ASCENDING-sorted build hash multiset `sorted_hash` — exactly
    ``join._probe_bounds``'s contract (lower/upper are left/right insertion
    positions, equal when there is no match). `overflow` is a scalar device
    bool: True means some probe row's run extends past the window and the
    result must be discarded (the dispatch layer's deferred-flag protocol
    re-runs the exact sort path)."""
    m = sorted_hash.shape[0]
    n = probe_hash.shape[0]
    bits = int(nbuckets).bit_length() - 1
    # bucket starts: one O(m) segment count over the already-sorted lane —
    # bucket-major order IS sort order, so starts[b] .. starts[b+1] is
    # bucket b's contiguous run
    counts = jax.ops.segment_sum(jnp.ones((m,), jnp.int32),
                                 _bucket_of(sorted_hash, bits),
                                 num_segments=nbuckets)
    starts = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)])
    kernel = functools.partial(_probe_kernel, bits=bits, window=window,
                               bsteps=int(m).bit_length())
    lower, cnt, ovf = pl.pallas_call(
        kernel,
        grid=(n // block,),
        in_specs=[pl.BlockSpec((nbuckets + 1,), lambda i: (0,)),
                  pl.BlockSpec((m,), lambda i: (0,)),
                  pl.BlockSpec((block,), lambda i: (i,))],
        out_specs=[pl.BlockSpec((block,), lambda i: (i,)),
                   pl.BlockSpec((block,), lambda i: (i,)),
                   pl.BlockSpec((1,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((n,), jnp.int32),
                   jax.ShapeDtypeStruct((n,), jnp.int32),
                   jax.ShapeDtypeStruct((1,), jnp.bool_)],
        interpret=interpret,
    )(starts, sorted_hash, probe_hash)
    return lower, lower + cnt, ovf[0]


# ---------------------------------------------------------------------------
# 2. one-pass blocked hash aggregation
# ---------------------------------------------------------------------------

# kernel op vocabulary: ("count",) consumes [valid]; ("sum",) consumes
# [valid, value] and accumulates in the value's dtype; ("min",)/("max",)
# consume [valid, lane] and emit (best lane, winning row position)
_OP_NIN = {"count": 1, "sum": 2, "min": 2, "max": 2}
_OP_NOUT = {"count": 1, "sum": 1, "min": 2, "max": 2}


def _ident_for(op: str, dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if op == "min" else info.min, dtype)


def _segagg_kernel(*refs, ops: tuple, nbuckets: int, ways: int, block: int):
    n_in = 2 + sum(_OP_NIN[op] for op in ops)
    packed_ref, live_ref = refs[0], refs[1]
    in_refs = refs[2:n_in]
    key_ref, cnt_ref = refs[n_in], refs[n_in + 1]
    out_refs = refs[n_in + 2:-1]
    ovf_ref = refs[-1]
    table_rows = nbuckets * ways

    @pl.when(pl.program_id(0) == 0)
    def _():
        key_ref[...] = jnp.full_like(key_ref, EMPTY_KEY)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)
        ovf_ref[...] = jnp.zeros_like(ovf_ref)
        oi = 0
        for op in ops:
            if op == "count" or op == "sum":
                out_refs[oi][...] = jnp.zeros_like(out_refs[oi])
                oi += 1
            else:  # min / max: identity lane + "no winner yet" positions
                out_refs[oi][...] = jnp.full_like(
                    out_refs[oi], _ident_for(op, out_refs[oi].dtype))
                out_refs[oi + 1][...] = jnp.full_like(out_refs[oi + 1],
                                                      _BIG_POS)
                oi += 2

    pk = packed_ref[...].astype(jnp.int64)
    lv = live_ref[...]
    # full splitmix64 finalizer for the bucket base (the packed lane is a
    # dense digit string; weakly-mixed low bits would pile correlated
    # groups into a few buckets and exhaust their ways)
    ux = pk.astype(jnp.uint64)
    ux = ux ^ (ux >> np.uint64(30))
    ux = ux * np.uint64(0xBF58476D1CE4E5B9)
    ux = ux ^ (ux >> np.uint64(27))
    ux = ux * np.uint64(0x94D049BB133111EB)
    ux = ux ^ (ux >> np.uint64(31))
    base = (ux.astype(jnp.int64) & np.int64(nbuckets - 1)).astype(jnp.int32) \
        * np.int32(ways)

    keys = key_ref[...]
    rem = lv
    place = jnp.zeros(pk.shape, jnp.int32)
    placed = jnp.zeros(pk.shape, bool)
    # search phase: the key may already be stored anywhere in its bucket
    for way in range(ways):
        tgt = base + way
        hit = rem & (jnp.take(keys, tgt) == pk)
        place = jnp.where(hit, tgt, place)
        placed = placed | hit
        rem = rem & ~hit
    # insert phase: claim the first EMPTY slot (scatter-max arbitrates
    # same-slot races; losers retry the next way). Occupied slots are never
    # overwritten — only rows that saw EMPTY attempt the claim, and a row
    # whose key was just claimed by an equal-key sibling matches on re-read.
    for way in range(ways):
        tgt = base + way
        stored0 = jnp.take(keys, tgt)
        attempt = rem & (stored0 == EMPTY_KEY)
        keys = keys.at[jnp.where(attempt, tgt, table_rows)].max(
            pk, mode="drop")
        hit = rem & (jnp.take(keys, tgt) == pk)
        place = jnp.where(hit, tgt, place)
        placed = placed | hit
        rem = rem & ~hit
    key_ref[...] = keys
    # bucket exhausted for some live row: the whole result is invalid
    ovf_ref[...] = ovf_ref[...] | jnp.any(rem)

    live_tgt = jnp.where(placed, place, table_rows)
    cnt_ref[...] = cnt_ref[...].at[live_tgt].add(
        jnp.ones(pk.shape, jnp.int64), mode="drop")

    pos = (pl.program_id(0) * block +
           jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0])
    ri = 0
    oi = 0
    for op in ops:
        valid = in_refs[ri][...]
        tgt = jnp.where(placed & valid, place, table_rows)
        if op == "count":
            out_refs[oi][...] = out_refs[oi][...].at[tgt].add(
                jnp.ones(pk.shape, jnp.int64), mode="drop")
        elif op == "sum":
            val = in_refs[ri + 1][...]
            out_refs[oi][...] = out_refs[oi][...].at[tgt].add(
                val, mode="drop")
        else:  # min / max, with winner-position tracking
            val = in_refs[ri + 1][...]
            cur = out_refs[oi][...]
            red = cur.at[tgt].min(val, mode="drop") if op == "min" \
                else cur.at[tgt].max(val, mode="drop")
            # a strictly better value invalidates earlier winners'
            # positions; equal values keep the smallest position (the sort
            # path's "first winning row" tie-break)
            improved = red < cur if op == "min" else red > cur
            post = out_refs[oi + 1][...]
            post = jnp.where(improved, _BIG_POS, post)
            cand = placed & valid & (val == jnp.take(red, place))
            post = post.at[jnp.where(cand, place, table_rows)].min(
                pos, mode="drop")
            out_refs[oi][...] = red
            out_refs[oi + 1][...] = post
        ri += _OP_NIN[op]
        oi += _OP_NOUT[op]


def hash_segagg(packed: jax.Array, live: jax.Array, ops: tuple,
                op_inputs: list, nbuckets: int, ways: int, block: int,
                interpret: bool):
    """One-pass blocked hash aggregation. `packed` is an EXACT int group-key
    lane (>= 0; ``kernels.pack_key_lane``), `ops` a static tuple over the
    vocabulary above, `op_inputs` the matching flat list of [capacity]
    arrays. Returns (key_table, live_count_table, [per-op tables...],
    overflow) where tables have `nbuckets * ways` rows; `overflow` True
    means some bucket ran out of ways and the caller must fall back to the
    sort path."""
    n = packed.shape[0]
    table_rows = nbuckets * ways
    kernel = functools.partial(_segagg_kernel, ops=ops, nbuckets=nbuckets,
                               ways=ways, block=block)
    blk_spec = pl.BlockSpec((block,), lambda i: (i,))
    tbl_spec = pl.BlockSpec((table_rows,), lambda i: (0,))
    out_specs = [tbl_spec, tbl_spec]
    out_shape = [jax.ShapeDtypeStruct((table_rows,), jnp.int64),
                 jax.ShapeDtypeStruct((table_rows,), jnp.int64)]
    ii = 0
    for op in ops:
        if op == "count":
            out_specs.append(tbl_spec)
            out_shape.append(jax.ShapeDtypeStruct((table_rows,), jnp.int64))
        elif op == "sum":
            out_specs.append(tbl_spec)
            out_shape.append(jax.ShapeDtypeStruct(
                (table_rows,), op_inputs[ii + 1].dtype))
        else:
            out_specs.extend([tbl_spec, tbl_spec])
            out_shape.extend([
                jax.ShapeDtypeStruct((table_rows,), op_inputs[ii + 1].dtype),
                jax.ShapeDtypeStruct((table_rows,), jnp.int32)])
        ii += _OP_NIN[op]
    out_specs.append(pl.BlockSpec((1,), lambda i: (0,)))
    out_shape.append(jax.ShapeDtypeStruct((1,), jnp.bool_))
    outs = pl.pallas_call(
        kernel,
        grid=(n // block,),
        in_specs=[blk_spec, blk_spec] + [blk_spec] * len(op_inputs),
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(packed.astype(jnp.int64), live, *op_inputs)
    return outs[0], outs[1], list(outs[2:-1]), outs[-1][0]


# ---------------------------------------------------------------------------
# 3. fused multi-column gather
# ---------------------------------------------------------------------------

def _gather_kernel(idx_ref, *refs, ncols: int):
    idx = idx_ref[...]
    for k in range(ncols):
        src = refs[k][...]
        refs[ncols + k][...] = jnp.take(
            src, jnp.clip(idx, 0, src.shape[0] - 1))


def fused_gather(cols: list, idx: jax.Array, block: int,
                 interpret: bool) -> list:
    """Gather every lane in `cols` by the shared index vector in ONE kernel:
    the index block is read once per grid step and all columns gather
    against it (vs one XLA gather op — one full pass over `idx` — per
    lane). Out-of-range indices clamp, matching ``jnp.take``'s default."""
    n = idx.shape[0]
    kernel = functools.partial(_gather_kernel, ncols=len(cols))
    outs = pl.pallas_call(
        kernel,
        grid=(n // block,),
        in_specs=[pl.BlockSpec((block,), lambda i: (i,))] +
                 [pl.BlockSpec(c.shape, lambda i: (0,)) for c in cols],
        out_specs=[pl.BlockSpec((block,), lambda i: (i,)) for _ in cols],
        out_shape=[jax.ShapeDtypeStruct((n,), c.dtype) for c in cols],
        interpret=interpret,
    )(idx, *cols)
    return list(outs)


# ---------------------------------------------------------------------------
# 4. ragged match materialization (join expand)
# ---------------------------------------------------------------------------

def _match_kernel(pre_ref, cnt_ref, own_ref, ovf_ref, *, window: int,
                  block: int, match_cap: int):
    """One probe-row block: each row claims its own run of output slots
    [prefix, prefix+count) in the match-capacity-resident owner table — the
    runs are disjoint (prefix is the exclusive cumsum of counts), so at most
    one row writes any slot and scatter-max is exact, not an arbitration.
    Rows whose run extends past the bounded `window` leave slots unclaimed
    and raise the overflow flag (the dispatch layer's deferred-flag protocol
    re-runs the exact expand). Slots no live run covers keep the init value
    0 — they differ from the sort path's scan-filled owners but are dead by
    construction (`offset`/`in_range` masking in ``join.expand_phase``)."""
    @pl.when(pl.program_id(0) == 0)
    def _():
        own_ref[...] = jnp.zeros_like(own_ref)
        ovf_ref[...] = jnp.zeros_like(ovf_ref)

    p = pre_ref[...]
    cnt = cnt_ref[...]
    pos = (pl.program_id(0) * block +
           jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0])
    own = own_ref[...]
    for off in range(window):
        tgt = jnp.where((off < cnt) & (p + off < match_cap), p + off,
                        match_cap)
        own = own.at[tgt].max(pos, mode="drop")
    own_ref[...] = own
    ovf_ref[...] = ovf_ref[...] | jnp.any(cnt > window)


def match_owner_table(prefix: jax.Array, counts: jax.Array, match_cap: int,
                      window: int, block: int, interpret: bool):
    """(owner, overflow): `owner[j]` is the probe row whose match run covers
    output slot `j`, for every live slot `j < total` — the values
    ``join.expand_phase`` derives from its owner-scatter + associative-scan
    chain, produced in one blocked pass with a bounded per-row emission
    window (the Ragged-Paged-Attention idiom shared with `_probe_kernel`).
    `overflow` True means some row's run exceeded the window and the result
    must be discarded."""
    cap_l = counts.shape[0]
    pre = jnp.clip(prefix, 0, match_cap).astype(jnp.int32)
    kernel = functools.partial(_match_kernel, window=window, block=block,
                               match_cap=match_cap)
    own, ovf = pl.pallas_call(
        kernel,
        grid=(cap_l // block,),
        in_specs=[pl.BlockSpec((block,), lambda i: (i,)),
                  pl.BlockSpec((block,), lambda i: (i,))],
        out_specs=[pl.BlockSpec((match_cap,), lambda i: (0,)),
                   pl.BlockSpec((1,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((match_cap,), jnp.int32),
                   jax.ShapeDtypeStruct((1,), jnp.bool_)],
        interpret=interpret,
    )(pre, counts.astype(jnp.int32))
    return own, ovf[0]


# ---------------------------------------------------------------------------
# 5. blocked partial top-k (sort_limit)
# ---------------------------------------------------------------------------

def _topk_kernel(key_ref, okey_ref, opos_ref, *, k: int, block: int):
    """One input block: select the block's k smallest packed keys by k
    static rounds of (min, first-position-of-min), emitting (key, position)
    candidates in ascending key order with position-ascending ties — the
    stable argsort's order. Dead rows carry the displaced MAX sentinel and
    only surface when a block has fewer than k live rows."""
    keys = key_ref[...]
    pos = (pl.program_id(0) * block +
           jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0])
    sentinel = jnp.asarray(jnp.iinfo(keys.dtype).max, keys.dtype)
    ok = jnp.zeros((k,), keys.dtype)
    op = jnp.zeros((k,), jnp.int32)
    cur = keys
    for j in range(k):
        m = jnp.min(cur)
        wp = jnp.min(jnp.where(cur == m, pos, _BIG_POS))
        ok = ok.at[j].set(m)
        op = op.at[j].set(wp)
        cur = jnp.where(pos == wp, sentinel, cur)
    okey_ref[...] = ok
    opos_ref[...] = op


def blocked_topk(sort_key: jax.Array, k: int, block: int, interpret: bool):
    """(keys, positions) of each block's k smallest entries in the packed
    sort-key lane — `n // block` candidate groups of k, in block-major
    order. The global k smallest are a subset of the candidates (every
    block contributes its own k smallest), and a stable argsort over the
    flattened candidate keys reproduces the full lane's stable order for
    the first k: within a block ties are emitted position-ascending, and
    across blocks the flattened (block-major) order IS position-ascending."""
    n = sort_key.shape[0]
    kernel = functools.partial(_topk_kernel, k=k, block=block)
    keys, pos = pl.pallas_call(
        kernel,
        grid=(n // block,),
        in_specs=[pl.BlockSpec((block,), lambda i: (i,))],
        out_specs=[pl.BlockSpec((k,), lambda i: (i,)),
                   pl.BlockSpec((k,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct(((n // block) * k,), sort_key.dtype),
                   jax.ShapeDtypeStruct(((n // block) * k,), jnp.int32)],
        interpret=interpret,
    )(sort_key)
    return keys, pos


# ---------------------------------------------------------------------------
# 6. exchange hash + partition scatter
# ---------------------------------------------------------------------------

# hash64 constants — MUST match cluster/exchange.py bit for bit: both sides
# of an exchange (device-routing sender, numpy-routing receiver) must agree
# on bucket placement with no coordination
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_MIX = np.uint64(0xC2B2AE3D27D4EB4F)
_SEED = np.uint64(0x243F6A8885A308D3)


def _scatter_kernel(*refs, ncols: int, nbuckets: int):
    """One row block: finish the per-column hash (golden-ratio multiply +
    xor-shift over the canonical pre-mix value lanes), fold the columns into
    the seeded combined key hash, take the bucket id from the high bits, and
    scatter-add the per-bucket counts into the resident histogram — numpy's
    `_hash_column` + `key_hash` + `bucket_ids` + `bincount` chain, fused
    into one pass over the rows."""
    val_refs = refs[:ncols]
    live_ref = refs[ncols]
    pid_ref, cnt_ref = refs[ncols + 1], refs[ncols + 2]

    @pl.when(pl.program_id(0) == 0)
    def _():
        cnt_ref[...] = jnp.zeros_like(cnt_ref)

    blk = live_ref.shape[0]
    h = jnp.full((blk,), _SEED, jnp.uint64)
    for c in range(ncols):
        v = val_refs[c][...].astype(jnp.uint64) * _GOLDEN
        v = v ^ (v >> np.uint64(29))
        h = (h ^ v) * _MIX
        h = h ^ (h >> np.uint64(33))
    pid = ((h >> np.uint64(17)) % np.uint64(nbuckets)).astype(jnp.int32)
    pid_ref[...] = pid
    lv = live_ref[...]
    cnt_ref[...] = cnt_ref[...].at[jnp.where(lv, pid, nbuckets)].add(
        jnp.ones((blk,), jnp.int64), mode="drop")


def hash_scatter(val_lanes: list, live: jax.Array, nbuckets: int, block: int,
                 interpret: bool):
    """(bucket_ids, counts) over the padded canonical row lanes: per-row
    exchange bucket ids (int32, identical to ``exchange.bucket_ids``) and
    the per-bucket live-row histogram (int64, identical to ``np.bincount``
    over the unpadded rows)."""
    n = live.shape[0]
    kernel = functools.partial(_scatter_kernel, ncols=len(val_lanes),
                               nbuckets=nbuckets)
    blk_spec = pl.BlockSpec((block,), lambda i: (i,))
    pid, counts = pl.pallas_call(
        kernel,
        grid=(n // block,),
        in_specs=[blk_spec] * (len(val_lanes) + 1),
        out_specs=[blk_spec, pl.BlockSpec((nbuckets,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((n,), jnp.int32),
                   jax.ShapeDtypeStruct((nbuckets,), jnp.int64)],
        interpret=interpret,
    )(*val_lanes, live)
    return pid, counts
