"""QueryEngine: the session object.

Parity target: reference `QueryEngine` (crates/engine/src/lib.rs:28-62) — a session
wrapping catalog + UDFs with `register_table` and `execute(sql) -> batches` — but
the execution stack underneath is ours end-to-end (parse -> bind -> optimize ->
device execution), not a DataFusion delegation, and errors are raised as
IglooError instead of panicking (reference gap G9: lib.rs:55-56 uses `.expect`).

The built-in `capitalize` UDF mirrors the reference's
(crates/engine/src/lib.rs:71-95: first char upper, rest lower, NULL-preserving).
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import pyarrow as pa

from igloo_tpu import types as T
from igloo_tpu.catalog import Catalog, MemTable, TableProvider
from igloo_tpu.errors import CatalogError, IglooError, PlanError, \
    SnapshotChanged
from igloo_tpu.storage import snapshot as storage_snapshot
from igloo_tpu.exec.executor import Executor
from igloo_tpu.plan import logical as L
from igloo_tpu.plan.binder import Binder
from igloo_tpu.plan.optimizer import last_adaptive_decisions, optimize
from igloo_tpu.sql import ast as A
from igloo_tpu.sql.parser import parse_sql
from igloo_tpu.utils import stats, tracing, watch
from igloo_tpu.utils.tracing import span


@dataclass
class UdfDef:
    """Scalar UDF type signature; execution happens in the expression compiler
    (string UDFs run over dictionaries host-side, numeric ones as jnp lanes)."""
    name: str
    result: T.DataType

    def return_type(self, arg_types):
        return self.result


@dataclass
class QueryResult:
    table: pa.Table
    plan: Optional[L.LogicalPlan] = None
    elapsed_s: float = 0.0
    # per-query telemetry (operator tree, tier, transfer bytes, counter
    # deltas) — populated for SELECT and EXPLAIN ANALYZE
    stats: Optional[stats.QueryStats] = None

    @property
    def num_rows(self) -> int:
        return self.table.num_rows


# process default for QueryEngine(mesh=...): "auto" row-shards across all
# local devices when more than one is visible; the test suite pins this to
# None so the 8-virtual-device CPU mesh exercises single-device paths unless a
# test opts in explicitly
DEFAULT_MESH: object = "auto"


class QueryEngine:
    def __init__(self, catalog: Optional[Catalog] = None, use_jit: bool = True,
                 cache_budget_bytes: int = 1 << 30,
                 chunk_budget_bytes: int = 2 << 30,
                 mesh: object = "default"):
        if mesh == "default":
            mesh = DEFAULT_MESH
        from igloo_tpu.exec.cache import BatchCache
        self.catalog = catalog if catalog is not None else Catalog()
        self.udfs: dict[str, UdfDef] = {}
        self._jit_cache: dict = {}
        self._use_jit = use_jit
        # source tables whose estimated DEVICE-LANE size exceeds this
        # execute partition-at-a-time (exec/chunked.py) or via
        # GRACE-partitioned joins (exec/grace.py) instead of as one
        # DeviceBatch. Comparisons use estimated_lane_bytes (file estimates
        # x the provider's bytes_expansion): SF10's 1.2 GB parquet lineitem
        # decodes to ~4 GB of int64/float64 lanes, and its full-width join
        # intermediates at 67M lanes crash a 16 GB-HBM chip if run
        # monolithically
        self.chunk_budget_bytes = chunk_budget_bytes
        # multi-chip execution: "auto" = row-shard across all local devices
        # when more than one is visible (parallel/ShardedExecutor); None =
        # single-device; or an explicit jax.sharding.Mesh
        self._mesh_setting = mesh
        self._mesh = None
        # per-THREAD demotion overrides (serving degradation ladder,
        # docs/serving.md): a constrained chunk budget forces the chunked/
        # GRACE tiers, force_host the numpy tier — thread-local because the
        # coordinator runs concurrent queries through ONE engine and only
        # the demoted query must execute constrained
        self._demote_tls = threading.local()
        # HBM batch cache: scan results stay device-resident across queries
        # (the real version of the reference's unenforced CacheConfig, gap G7)
        self.batch_cache = BatchCache(cache_budget_bytes)
        # host-side query-result cache (the reference cache's actual shape:
        # query -> batches, crates/cache/src/lib.rs:20-56), snapshot-validated
        from igloo_tpu.exec.result_cache import ResultCache
        self.result_cache = ResultCache()
        # persistent cardinality hints for adaptive fused execution (beside the
        # XLA compile cache, so a fresh process compiles hinted programs first)
        from igloo_tpu.exec.hints import default_store
        self.hint_store = default_store()
        # plans whose scanned sources total under this many bytes execute on
        # the host when the default device is a (tunneled) accelerator: a
        # dispatch+fetch through the tunnel costs ~0.1-0.3 s, so a query over
        # a few MB can never beat host execution there (round-4 verdict weak
        # #3: q2/q11/q16). The host tier uses the numpy executor
        # (exec/host.py) when it supports the plan; XLA:CPU is NOT used (on
        # small hosts its sort kernels lose to numpy by ~3x and its AOT cache
        # entries must not mix with the TPU cache). 0 disables the fast path.
        self.host_route_bytes = int(os.environ.get(
            "IGLOO_HOST_ROUTE_BYTES", str(64 << 20)))
        # decoded-column cache for the host tier (plain RAM, not HBM)
        self.host_cache = BatchCache(cache_budget_bytes)
        # reference parity: capitalize registered at construction (lib.rs:41-42)
        self.register_udf(UdfDef("capitalize", T.STRING))
        # SQL-queryable telemetry: SELECT * FROM system.metrics /
        # system.query_log through the normal engine path (system_tables.py)
        from igloo_tpu.system_tables import register_system_tables
        register_system_tables(self.catalog)

    # --- registration ---

    def register_table(self, name: str, provider) -> None:
        if isinstance(provider, pa.Table):
            provider = MemTable(provider)
        self.catalog.register(name, provider)
        # a replaced provider's id() can be reused by the allocator, so identity
        # tokens alone cannot be trusted across re-registration — evict eagerly
        self.batch_cache.invalidate_table(name.lower())
        self.host_cache.invalidate_table(name.lower())
        self.result_cache.invalidate_table(name)

    def deregister_table(self, name: str) -> None:
        self.catalog.deregister(name)
        self.batch_cache.invalidate_table(name.lower())
        self.host_cache.invalidate_table(name.lower())
        self.result_cache.invalidate_table(name)

    def register_udf(self, udf: UdfDef) -> None:
        self.udfs[udf.name.lower()] = udf

    # --- execution ---

    def plan(self, sql: str) -> L.LogicalPlan:
        stmt = parse_sql(sql)
        if not isinstance(stmt, A.SelectStmt):
            raise PlanError("plan() requires a SELECT statement")
        bound = Binder(self.catalog, udfs=self.udfs).bind(stmt)
        return optimize(bound)

    def execute(self, sql: str) -> pa.Table:
        return self.query(sql).table

    # alias mirroring a Python-session feel
    def sql(self, sql: str) -> pa.Table:
        return self.execute(sql)

    def query(self, sql: str) -> QueryResult:
        t0 = time.perf_counter()
        stmt = parse_sql(sql)
        if isinstance(stmt, A.ShowTablesStmt):
            return QueryResult(pa.table({"table_name": self.catalog.names()}),
                               elapsed_s=time.perf_counter() - t0)
        if isinstance(stmt, A.DescribeStmt):
            schema = self.catalog.get(stmt.table).schema()
            return QueryResult(pa.table({
                "column_name": schema.names,
                "data_type": [repr(f.dtype) for f in schema],
                "nullable": [f.nullable for f in schema],
            }), elapsed_s=time.perf_counter() - t0)
        if isinstance(stmt, A.ExplainStmt):
            bound = Binder(self.catalog, udfs=self.udfs).bind(stmt.query)
            plan = optimize(bound)
            text = L.plan_tree_str(plan)
            for d in last_adaptive_decisions():
                # adaptive reorder attribution (docs/adaptive.md): which
                # greedy order won and whether observations or estimates
                # drove it
                text += (f"\n-- adaptive: strategy={d['strategy']} "
                         f"join_order={d['join_order']} "
                         f"adaptive_source={d['adaptive_source']}")
            qs = None
            if stmt.analyze:
                # EXPLAIN ANALYZE executes through the SAME routing ladder as
                # a real query (host / chunked / GRACE / normal), with stats
                # collection in DETAIL mode: actual per-operator row counts,
                # per-node wall time, compile/execute split, transfer bytes,
                # and GRACE per-partition rollups (docs/observability.md)
                peak0 = stats.device_peak_hbm_bytes()

                def rebind() -> L.LogicalPlan:
                    b = Binder(self.catalog, udfs=self.udfs).bind(stmt.query)
                    return optimize(b)

                with stats.collect(sql, detail=True) as qs:
                    table, plan = self._execute_pinned(plan, rebind)
                    qs.rows = table.num_rows
                self._harvest_adaptive(qs, plan, peak_hbm0=peak0)
                text += "\n-- actual (operator tree):\n"
                text += stats.render_tree(qs)
                delta = qs.counters
                nparts = delta.get("grace.partitions", 0)
                if nparts:
                    text += f"\n-- grace.partitions: {nparts}"
                for ph in ("partition", "join", "merge"):
                    ms = delta.get(f"grace.{ph}_ms", 0)
                    if ms:
                        text += f"\n-- grace.{ph}_s: {ms / 1000:.3f}"
                # persistent XLA compile-cache traffic for THIS query (the
                # jax.monitoring hooks in igloo_tpu/compile_cache.py run on
                # the compiling thread, so the delta is exact)
                cc_hit = delta.get("compile_cache.hit", 0)
                cc_miss = delta.get("compile_cache.miss", 0)
                if cc_hit or cc_miss:
                    text += (f"\n-- compile_cache: hits={cc_hit} "
                             f"misses={cc_miss}")
                # object-store attribution (docs/storage.md): ranged reads,
                # policy retries, prefetcher hits, and whether the query
                # paid a snapshot re-plan
                sreads = delta.get("storage.read", 0)
                sretry = delta.get("storage.snapshot_retry", 0)
                if sreads or sretry:
                    text += (f"\n-- storage: reads={sreads} "
                             f"retries={delta.get('storage.retry', 0)} "
                             f"prefetch_hits="
                             f"{delta.get('storage.prefetch_hit', 0)} "
                             f"snapshot_retries={sretry}")
                # local mesh-tier attribution: did the sharded executor run,
                # across how many chips, at what per-device lane width (the
                # chip-level half of the two-level topology,
                # docs/distributed.md). Keyed on the TIER, not the upload
                # counters: a warm run serves row-sharded batches from the
                # scan cache (zero uploads) but still executes sharded
                uploads = delta.get("mesh.shard_uploads", 0)
                if uploads or qs.tier == "sharded":
                    mesh = self._resolve_mesh()
                    ndev = int(mesh.devices.size) if mesh is not None else 1
                    lanes = delta.get("mesh.sharded_lanes", 0)
                    text += (f"\n-- mesh: devices={ndev} "
                             f"shard_uploads={uploads}")
                    # lane width only when this query actually uploaded —
                    # a warm run's batches come from the scan cache and a
                    # zero-delta division would claim 0 lanes per device
                    if uploads:
                        text += (f" lanes_per_device="
                                 f"{lanes // uploads // max(ndev, 1)}")
                    else:
                        text += " (batches served from the scan cache)"
                if qs.trace_id:
                    # flight-recorder pointer: the executed query's stitched
                    # timeline, queryable in SQL or exportable for Perfetto
                    # (docs/observability.md#distributed-tracing)
                    text += (f"\n-- trace: {qs.trace_id} (SELECT * FROM "
                             "system.query_traces WHERE trace_id = "
                             f"'{qs.trace_id}'; coordinator 'trace' action; "
                             "IGLOO_TRACE_DIR)")
            return QueryResult(pa.table({"plan": text.split("\n")}), plan=plan,
                               elapsed_s=time.perf_counter() - t0, stats=qs)
        if isinstance(stmt, A.CreateTableAsStmt):
            res = self._run_select(stmt.query)
            self.register_table(stmt.name, MemTable(res))
            return QueryResult(pa.table({"status": [f"created {stmt.name}"]}),
                               elapsed_s=time.perf_counter() - t0)
        if isinstance(stmt, A.DropTableStmt):
            if stmt.name.lower() not in self.catalog and not stmt.if_exists:
                raise CatalogError(f"table not found: {stmt.name}")
            if stmt.name.lower() in self.catalog:
                # full deregistration: evicts the table's HBM batches and any
                # cached results sourced from it
                self.deregister_table(stmt.name)
            return QueryResult(pa.table({"status": [f"dropped {stmt.name}"]}),
                               elapsed_s=time.perf_counter() - t0)
        if isinstance(stmt, A.SelectStmt):
            peak0 = stats.device_peak_hbm_bytes()
            with stats.collect(sql) as qs:
                table, plan = self._run_select(stmt, want_plan=True)
                qs.rows = table.num_rows
            self._harvest_adaptive(qs, plan, peak_hbm0=peak0)
            return QueryResult(table, plan=plan,
                               elapsed_s=time.perf_counter() - t0, stats=qs)
        raise IglooError(f"unsupported statement {type(stmt).__name__}")

    def _harvest_adaptive(self, qs: Optional[stats.QueryStats],
                          plan: Optional[L.LogicalPlan],
                          peak_hbm0: int = 0) -> None:
        """Fold a finished query's free cardinality observations into the
        process-wide AdaptiveStats store (docs/adaptive.md): per-subtree
        observed rows, the root cardinality, and — when a join AND both of
        its inputs were observed in this query — the join's input total, so
        selectivity is derivable. Best-effort by contract: stale or missing
        stats mis-route plans, never break them."""
        from igloo_tpu.exec import hints
        peak = 0
        if qs is not None:
            # watchtower baseline check (docs/observability.md#watchtower):
            # BEFORE the adaptive gate — the anomaly detector is independent
            # of IGLOO_ADAPTIVE (its own kill switch is IGLOO_WATCH, checked
            # inside check_query). Runs after stats.collect published the
            # trace, so an escalation's pin() finds it ring-resident.
            # The one post-query watermark read, shared with the adaptive
            # recorder below.
            peak = stats.device_peak_hbm_bytes()
            watch.check_query(
                hints.plan_fp(plan) if plan is not None else None,
                qs.elapsed_s, qs=qs, qid=str(qs.qid or ""),
                trace_id=qs.trace_id or "", sql=qs.sql, tier=qs.tier,
                hbm_bytes=(float(peak - peak_hbm0)
                           if peak > peak_hbm0 else 0.0))
        if qs is None or not hints.adaptive_enabled():
            return
        obs = {k: n for k, n in qs.observations if k is not None}
        root_fp = hints.plan_fp(plan) if plan is not None else None
        if root_fp is not None and qs.rows is not None:
            obs[root_fp] = int(qs.rows)
        # device-memory watermark for the admission gate (docs/serving.md).
        # The watermark is process-CUMULATIVE (monotonic), so only a query
        # that RAISED it (`> peak_hbm0`, the caller's pre-query snapshot)
        # may record — otherwise every small query after one big one would
        # inherit the global peak, ratchet its prediction past the serving
        # budget, and demote forever. The recorded value is still an upper
        # bound involving this query, which is the right direction.
        peak_hbm = 0
        if root_fp is not None:
            peak_hbm = peak
            if peak_hbm <= peak_hbm0:
                peak_hbm = 0
        if not obs and not peak_hbm:
            return
        # the CURRENT process-wide store, not one cached at engine
        # construction: reset_adaptive_store() (tests) would otherwise leave
        # a long-lived engine recording into a store no planner reads
        store = hints.adaptive_store()
        for k, n in obs.items():
            store.observe(k, rows=n)
        if peak_hbm:
            store.observe(root_fp, peak_hbm_bytes=int(peak_hbm))
        if plan is not None:
            for node in L.walk_plan(plan):
                if isinstance(node, L.Join):
                    jf = hints.plan_fp(node)
                    lf = hints.plan_fp(node.left)
                    rf = hints.plan_fp(node.right)
                    if jf in obs and lf in obs and rf in obs:
                        store.observe(jf, in_rows=obs[lf] + obs[rf])
        store.flush()
        tracing.counter("adaptive.observed", len(obs))

    @contextlib.contextmanager
    def demoted(self, budget_bytes: Optional[int] = None,
                force_host: bool = False):
        """Run the enclosed executions on this thread one rung down the
        degradation ladder (docs/serving.md): a constrained `budget_bytes`
        makes `_execute_plan` route over-budget plans to the chunked/GRACE
        tiers at THAT budget, `force_host` routes supported plans to the
        numpy host tier regardless of backend. The serving front door uses
        this when a query hits RESOURCE_EXHAUSTED/MemoryError (or is
        predicted past the whole HBM budget) instead of failing it."""
        prev = (getattr(self._demote_tls, "budget", None),
                getattr(self._demote_tls, "force_host", False))
        self._demote_tls.budget = budget_bytes
        self._demote_tls.force_host = force_host
        try:
            yield
        finally:
            self._demote_tls.budget, self._demote_tls.force_host = prev

    def _chunk_budget(self) -> int:
        override = getattr(self._demote_tls, "budget", None)
        if override is not None:
            return min(int(override), self.chunk_budget_bytes)
        return self.chunk_budget_bytes

    def _resolve_mesh(self):
        """The execution mesh, resolved once: None for single-device."""
        if self._mesh is None and self._mesh_setting is not None:
            from igloo_tpu.parallel.mesh import resolve_mesh
            self._mesh = resolve_mesh(self._mesh_setting)
            if self._mesh is None:
                self._mesh_setting = None
        return self._mesh

    def _executor(self) -> Executor:
        mesh = self._resolve_mesh()
        if mesh is not None:
            from igloo_tpu.parallel.executor import ShardedExecutor
            return ShardedExecutor(self._jit_cache, use_jit=self._use_jit,
                                   batch_cache=self.batch_cache, mesh=mesh)
        return Executor(self._jit_cache, use_jit=self._use_jit,
                        batch_cache=self.batch_cache, hints=self.hint_store)

    def _host_route(self, plan: L.LogicalPlan) -> bool:
        """True when every scanned source is sized and the total is under
        host_route_bytes while the default backend is an accelerator."""
        if self.host_route_bytes <= 0:
            return False
        import jax
        if jax.default_backend() == "cpu":
            return False
        from igloo_tpu.plan.optimizer import _est_scan_bytes
        total = _est_scan_bytes(plan, include_subqueries=True)
        return total is not None and total <= self.host_route_bytes

    def _execute_plan(self, plan: L.LogicalPlan) -> pa.Table:
        """The full routing ladder shared by _run_select and EXPLAIN ANALYZE:
        host tier (small sources on a tunneled accelerator) -> chunked tier
        (decomposable aggregates over big scans) -> GRACE tier (over-budget
        join trees, exec/grace.py) -> normal executor. A resolved multi-chip
        mesh takes precedence over single-device chunking / out-of-core: the
        sharded executor already bounds per-chip memory by row-sharding, and
        silently chunking would discard the parallelism."""
        from igloo_tpu.exec.chunked import LocalChunkExecutor, chunk_count
        qs = stats.current()
        budget = self._chunk_budget()
        force_host = getattr(self._demote_tls, "force_host", False)
        if force_host or self._host_route(plan):
            from igloo_tpu.exec.host import HostExecutor, HostUnsupported
            try:
                with span("execute"):
                    table = HostExecutor(
                        self.catalog,
                        scan_cache=self.host_cache).execute_to_arrow(plan)
                tracing.counter("engine.host_route")
                if qs is not None:
                    qs.tier = "host"
                return table
            except HostUnsupported as e:
                tracing.counter("engine.host_route_unsupported")
                tracing.counter(
                    f"engine.host_route_unsupported.{e.args[0] if e.args else ''}")
            except MemoryError:
                # a host-tier allocation blowup (e.g. a grouped cardinality
                # the direct-slot guards missed) must degrade to the device
                # tier, not fail the query
                tracing.counter("engine.host_route_oom")
        mesh = self._resolve_mesh()
        chunks = 0 if mesh is not None else chunk_count(plan, budget)
        grace_found = None
        if mesh is None and not chunks:
            from igloo_tpu.exec.grace import find_grace_join
            grace_found = find_grace_join(plan, budget)
        with span("execute"):
            if chunks:
                tracing.counter("engine.chunked_route")
                if qs is not None:
                    qs.tier = "chunked"
                return LocalChunkExecutor(
                    self.catalog, self._jit_cache, use_jit=self._use_jit,
                    batch_cache=self.batch_cache,
                    chunks=chunks).execute_to_arrow(plan)
            if grace_found:
                from igloo_tpu.exec.grace import GraceJoinExecutor
                tracing.counter("engine.grace_route")
                if qs is not None:
                    qs.tier = "grace"
                return GraceJoinExecutor(
                    self.catalog, self._jit_cache, use_jit=self._use_jit,
                    batch_cache=self.batch_cache, hints=self.hint_store,
                    budget_bytes=budget,
                ).execute_to_arrow(plan, grace_found)
            if qs is not None:
                qs.tier = "sharded" if mesh is not None else "device"
            return self._executor().execute_to_arrow(plan)

    def _execute_pinned(self, plan: L.LogicalPlan, rebind):
        """Execute under a pinned storage snapshot (storage/snapshot.py):
        every provider's first snapshot() pins the etags all ranged reads
        then verify. A source mutated mid-query raises SnapshotChanged; the
        engine converts it into exactly ONE re-plan at the new snapshot
        (counter `storage.snapshot_retry`) — caches for the changed table
        dropped, plan re-bound via `rebind()`, execution re-pinned. A
        second mutation during the retry propagates: a source churning
        faster than the query can run is an error, not a livelock."""
        try:
            with storage_snapshot.pinned_scope():
                return self._execute_plan(plan), plan
        except SnapshotChanged as ex:
            tracing.counter("storage.snapshot_retry")
            from igloo_tpu.cluster import events
            events.emit("snapshot_retry", severity="warn",
                        table=ex.table or "")
            tracing.log.warning(
                "storage: snapshot changed mid-query (%s); re-planning once",
                ex)
            if ex.table:
                self.batch_cache.invalidate_table(ex.table)
                self.host_cache.invalidate_table(ex.table)
                self.result_cache.invalidate_table(ex.table)
            plan = rebind()
            with storage_snapshot.pinned_scope():
                return self._execute_plan(plan), plan

    def _run_select(self, stmt: A.SelectStmt, want_plan: bool = False):
        from igloo_tpu.exec.result_cache import plan_cache_key
        state: dict = {}

        def bind() -> L.LogicalPlan:
            with span("bind+optimize"):
                bound = Binder(self.catalog, udfs=self.udfs).bind(stmt)
                p = optimize(bound)
            state["rkey"] = plan_cache_key(p)
            return p

        plan = bind()
        if state["rkey"] is not None:
            hit = self.result_cache.get(state["rkey"])
            if hit is not None:
                qs = stats.current()
                if qs is not None:
                    qs.tier = "result_cache"
                return (hit, plan) if want_plan else hit
        table, plan = self._execute_pinned(plan, bind)
        if state["rkey"] is not None:
            self.result_cache.put(state["rkey"], table)
        if want_plan:
            return table, plan
        return table
