"""Connectors: table providers for external data sources (reference
crates/connectors/*: filesystem/iceberg working, postgres/mysql stubs — all real
here)."""
from igloo_tpu.connectors.csv import CsvTable  # noqa: F401
from igloo_tpu.connectors.parquet import ParquetTable  # noqa: F401

__all__ = ["CsvTable", "ParquetTable", "IcebergTable", "DbApiTable",
           "PostgresTable", "MySqlTable"]


def __getattr__(name):
    # lazy: avro/iceberg/dbapi pull extra machinery only when used
    if name == "IcebergTable":
        from igloo_tpu.connectors.iceberg import IcebergTable
        return IcebergTable
    if name in ("DbApiTable", "PostgresTable", "MySqlTable"):
        from igloo_tpu.connectors import dbapi
        return getattr(dbapi, name)
    raise AttributeError(name)
