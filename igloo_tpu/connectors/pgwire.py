"""Minimal pure-Python PostgreSQL wire-protocol (v3) client.

The reference declares a postgres connector and ships an empty crate
(crates/connectors/postgres/src/lib.rs:1). The federation core here
(connectors/dbapi.py) speaks to any DBAPI driver; this module removes the
"requires psycopg2" gap in environments without binary drivers: a small
DBAPI-shaped client that speaks the actual postgres wire protocol — startup,
cleartext/trust auth, simple Query ('Q'), RowDescription/DataRow decoding in
text format, and error surfacing.

Supported surface (what the connector needs): connect() -> Connection;
Connection.cursor(); Cursor.execute(sql); Cursor.description;
Cursor.fetchall(); close(). Results decode by type OID: ints, floats,
numeric, bool, text, date, timestamp.
"""
from __future__ import annotations

import datetime as _dt
import socket
import struct
from typing import Optional

PROTOCOL_V3 = 196608  # 3 << 16

# type OID -> python converter (text format)
_OID_BOOL = 16
_OID_INT8 = 20
_OID_INT2 = 21
_OID_INT4 = 23
_OID_TEXT = 25
_OID_FLOAT4 = 700
_OID_FLOAT8 = 701
_OID_VARCHAR = 1043
_OID_DATE = 1082
_OID_TIMESTAMP = 1114
_OID_NUMERIC = 1700


def _conv_for(oid: int):
    if oid in (_OID_INT2, _OID_INT4, _OID_INT8):
        return int
    if oid in (_OID_FLOAT4, _OID_FLOAT8, _OID_NUMERIC):
        return float
    if oid == _OID_BOOL:
        return lambda s: s == "t"
    if oid == _OID_DATE:
        return _dt.date.fromisoformat
    if oid == _OID_TIMESTAMP:
        return lambda s: _dt.datetime.fromisoformat(s)
    return lambda s: s


class PgWireError(Exception):
    pass


class Cursor:
    def __init__(self, conn: "Connection"):
        self._conn = conn
        self.description = None
        self._rows: list[tuple] = []

    def execute(self, sql: str) -> None:
        self.description, self._rows = self._conn._query(sql)

    def fetchall(self) -> list[tuple]:
        return self._rows

    def fetchone(self):
        return self._rows[0] if self._rows else None

    def close(self) -> None:
        pass


class Connection:
    """One TCP connection speaking the simple-query subprotocol."""

    def __init__(self, host: str = "127.0.0.1", port: int = 5432,
                 user: str = "igloo", dbname: str = "postgres",
                 password: Optional[str] = None, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""
        params = f"user\0{user}\0database\0{dbname}\0\0".encode()
        pkt = struct.pack("!ii", 8 + len(params), PROTOCOL_V3) + params
        self._sock.sendall(pkt)
        self._auth(password)
        # the connect timeout must not become a permanent read deadline: a
        # remote query legitimately taking longer would raise socket.timeout
        # mid-conversation (blocking mode matches psycopg2's default)
        self._sock.settimeout(None)

    # --- wire plumbing ---

    def _recv_msg(self):
        while len(self._buf) < 5:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise PgWireError("server closed connection")
            self._buf += chunk
        tag = self._buf[0:1]
        (length,) = struct.unpack("!i", self._buf[1:5])
        while len(self._buf) < 1 + length:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise PgWireError("server closed connection mid-message")
            self._buf += chunk
        body = self._buf[5: 1 + length]
        self._buf = self._buf[1 + length:]
        return tag, body

    def _send(self, tag: bytes, body: bytes) -> None:
        self._sock.sendall(tag + struct.pack("!i", 4 + len(body)) + body)

    @staticmethod
    def _error_message(body: bytes) -> str:
        fields = {}
        for part in body.split(b"\0"):
            if part:
                fields[chr(part[0])] = part[1:].decode("utf-8", "replace")
        return fields.get("M", "unknown server error")

    def _auth(self, password: Optional[str]) -> None:
        while True:
            tag, body = self._recv_msg()
            if tag == b"R":
                (code,) = struct.unpack("!i", body[:4])
                if code == 0:
                    continue  # AuthenticationOk
                if code == 3:  # cleartext password
                    if password is None:
                        raise PgWireError("server wants a password")
                    self._send(b"p", password.encode() + b"\0")
                    continue
                raise PgWireError(f"unsupported auth method {code} "
                                  "(only trust/cleartext)")
            elif tag in (b"S", b"K", b"N"):
                continue  # ParameterStatus / BackendKeyData / Notice
            elif tag == b"Z":
                return  # ReadyForQuery
            elif tag == b"E":
                raise PgWireError(self._error_message(body))
            else:
                raise PgWireError(f"unexpected message {tag!r} during startup")

    # --- queries ---

    def _query(self, sql: str):
        self._send(b"Q", sql.encode() + b"\0")
        description = None
        convs: list = []
        rows: list[tuple] = []
        error: Optional[str] = None
        while True:
            tag, body = self._recv_msg()
            if tag == b"T":  # RowDescription
                (nf,) = struct.unpack("!h", body[:2])
                off = 2
                description = []
                convs = []
                for _ in range(nf):
                    end = body.index(b"\0", off)
                    name = body[off:end].decode()
                    off = end + 1
                    _tbl, _col, oid, _len, _mod, _fmt = struct.unpack(
                        "!ihihih", body[off: off + 18])
                    off += 18
                    description.append((name, oid, None, None, None, None,
                                        None))
                    convs.append(_conv_for(oid))
            elif tag == b"D":  # DataRow
                (nf,) = struct.unpack("!h", body[:2])
                off = 2
                vals = []
                for i in range(nf):
                    (ln,) = struct.unpack("!i", body[off: off + 4])
                    off += 4
                    if ln == -1:
                        vals.append(None)
                    else:
                        raw = body[off: off + ln].decode()
                        off += ln
                        vals.append(convs[i](raw) if i < len(convs) else raw)
                rows.append(tuple(vals))
            elif tag == b"C":  # CommandComplete
                continue
            elif tag == b"E":
                error = self._error_message(body)
            elif tag == b"Z":  # ReadyForQuery: transaction boundary
                if error is not None:
                    raise PgWireError(error)
                return description, rows
            elif tag in (b"S", b"N"):
                continue
            else:
                raise PgWireError(f"unexpected message {tag!r} during query")

    def cursor(self) -> Cursor:
        return Cursor(self)

    def close(self) -> None:
        try:
            self._send(b"X", b"")
        except Exception:
            pass
        self._sock.close()


def connect(dsn: str = "", **kw) -> Connection:
    """DSN form: 'host=... port=... user=... dbname=... password=...'.
    URI DSNs ('postgresql://...') are not parsed here — reject loudly rather
    than silently connecting to defaults."""
    if "://" in dsn:
        raise PgWireError(
            "URI-style DSNs are not supported by the bundled pgwire driver; "
            "use 'host=... port=... user=... dbname=...' (or install "
            "psycopg2 for URI support)")
    params: dict = {}
    for part in dsn.split():
        if "=" not in part:
            raise PgWireError(f"malformed DSN fragment {part!r}")
        k, _, v = part.partition("=")
        params[k] = v
    params.update(kw)
    return Connection(
        host=params.get("host", "127.0.0.1"),
        port=int(params.get("port", 5432)),
        user=params.get("user", "igloo"),
        dbname=params.get("dbname", params.get("database", "postgres")),
        password=params.get("password"),
    )
