"""Parquet connector.

Replaces the reference's ParquetScanExec (crates/engine/src/operators/parquet_scan.rs:
40-85 — deprecated reader API, 1024-row batches through an mpsc channel). TPU
design: decode host-side via pyarrow's C++ Parquet reader with column projection
AND row-group pruning from pushed-down predicates (min/max statistics), then one
`device_put` of whole columns into HBM (exec/batch.from_arrow).

Every byte comes through the object-store layer (igloo_tpu/storage,
docs/storage.md): reads are policy-retried ranged GETs verified against the
query's pinned snapshot etags (a source mutated mid-query raises
`SnapshotChanged` → ONE engine re-plan, never a torn result), a vanished
file is a snapshot change rather than a raw FileNotFoundError, and a row
group whose bytes no longer parse is quarantined behind a typed
`CorruptObjectError` naming file + row group.
"""
from __future__ import annotations

import datetime as _dt
import glob as _glob
import os
from typing import Optional

import pyarrow as pa
import pyarrow.parquet as pq

from igloo_tpu.errors import ConnectorError, SnapshotChanged, StorageError
from igloo_tpu.exec.batch import schema_from_arrow
from igloo_tpu.plan import expr as E
from igloo_tpu.storage import local_store, quarantine
from igloo_tpu.storage import snapshot as _snapshot
from igloo_tpu.types import Schema


class ParquetTable:
    """One file, a directory of files, or a glob pattern — optionally on an
    explicit `store` (any storage.ObjectStore; default local filesystem)."""

    # deterministic file/row-group order -> scans may be cached per column
    stable_row_order = True
    # compressed columnar files decode to ~3-4x their size as int64/float64
    # device lanes (device-memory budgets scale estimates by this)
    bytes_expansion = 3.5

    def __init__(self, path: str, store=None):
        import threading
        self.path = path
        self._store = store if store is not None else local_store()
        self._parts = None  # lazy (file, row_group) partition index
        self._plock = threading.Lock()  # guards _files/_parts (Flight threads)
        self._files = _expand_store(self._store, path)
        if not self._files:
            raise ConnectorError(f"no parquet files at {path}")
        try:
            self._arrow_schema = pq.read_schema(
                self._store.open_input(self._files[0], table=path))
        except Exception as ex:  # corrupt/fake file (reference gap G8)
            raise ConnectorError(f"cannot read parquet schema from "
                                 f"{self._files[0]}: {ex}") from None
        self._schema = schema_from_arrow(self._arrow_schema)

    def schema(self) -> Schema:
        return self._schema

    def __deepcopy__(self, memo):
        # providers are SHARED by plan copies (plan/logical.copy_plan shares
        # them deliberately); expression deepcopies that reach a provider
        # through a bound subquery plan must not clone it — the partition
        # lock isn't picklable and cloning would fork cache identity
        return self

    def snapshot(self):
        """Cache/CDC token: changes when any underlying file's store etag
        changes (re-lists directory/glob paths so added files are seen — and
        drops the stale partition index when the file set moved). Inside a
        query's pinned scope (storage/snapshot.py) the first call pins the
        token AND the per-file etags every ranged read then verifies."""
        tok, _etags = _snapshot.pin(self, self._snapshot_now)
        return tok

    def _snapshot_now(self) -> tuple:
        files = _expand_store(self._store, self.path)
        with self._plock:
            if files and files != self._files:
                self._files = files
                self._parts = None
            files = list(self._files)
        return self._store.snapshot_token(files)

    def _partition_index(self) -> list[tuple[str, int]]:
        """(file, row_group) pairs — the scan's parallel/chunking unit. Row
        groups (not whole files) so a single large file still distributes
        across workers / chunks (reference analog: fixed 1024-row read batches,
        parquet_scan.rs:54, which never leave the single stream). Lock-guarded:
        Flight serves fragments on concurrent threads, and snapshot() may drop
        the index when the file set moves. A file that vanishes between the
        list and the metadata read is a SNAPSHOT CHANGE, not a crash: it is
        dropped here, and the pinned-etag verification on the surviving reads
        tells the engine to re-plan."""
        with self._plock:
            if self._parts is None:
                parts: list[tuple[str, int]] = []
                for f in self._files:
                    try:
                        n = pq.ParquetFile(
                            self._store.open_input(f, table=self.path)
                        ).metadata.num_row_groups
                    except (FileNotFoundError, SnapshotChanged):
                        continue  # vanished between list and head
                    except Exception:
                        n = 1
                    parts.extend((f, i) for i in range(max(n, 1)))
                self._parts = parts
            return self._parts

    def num_partitions(self) -> int:
        return len(self._partition_index())

    def partition_token(self) -> str:
        """Stable fingerprint of the (file, row_group) partition index. Plans
        capture it at planning time; read_scan_table verifies it before
        partitioned reads, so an index rebuilt mid-query (snapshot() re-glob
        after a file replace) errors instead of silently reading wrong rows
        when only the layout — not the length — changed."""
        import hashlib
        parts = self._partition_index()
        return hashlib.sha1(repr(parts).encode()).hexdigest()

    def estimated_bytes(self) -> Optional[int]:
        return self._store.files_bytes(self._files)

    def _open(self, path: str):
        """Open one data file for verified ranged reads: the etag pinned by
        this query's snapshot() (if any) is enforced at open and on every
        read; a vanished file maps to SnapshotChanged — the typed signal the
        engine converts into one bounded re-plan."""
        pins = _snapshot.pinned_etags(self)
        want = pins.get(path) if pins is not None else None
        try:
            return self._store.open_input(path, want_etag=want,
                                          table=self.path)
        except FileNotFoundError:
            raise SnapshotChanged(
                f"parquet file vanished: {path} (table {self.path})",
                table=self.path, key=path) from None

    def read(self, projection: Optional[list[str]] = None,
             filters: Optional[list] = None) -> pa.Table:
        tables = [self._read_file(f, projection, filters) for f in self._files]
        return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

    def read_partition(self, index: int, projection=None, filters=None) -> pa.Table:
        try:
            # the index is mutable (snapshot() re-lists): a planned partition
            # id that is now out of range means the file set shrank — a
            # SNAPSHOT CHANGE the engine converts into one bounded re-plan,
            # not a bare IndexError
            path, rg = self._partition_index()[index]
        except IndexError:
            raise SnapshotChanged(
                f"parquet partition {index} out of range for {self.path} "
                "(source files moved/replaced)", table=self.path) from None
        fh = self._open(path)
        quarantine.check(path, fh.etag, rg, table=self.path)
        try:
            pf = pq.ParquetFile(fh)
            if rg >= pf.metadata.num_row_groups:
                # the file shrank under an unpinned read: a snapshot change
                # (never corruption — the bytes parse fine)
                raise SnapshotChanged(
                    f"parquet file {path} has {pf.metadata.num_row_groups} "
                    f"row groups, planned index {rg} (table {self.path})",
                    table=self.path, key=path)
            groups = _prune_row_groups(pf, filters)
            if groups is not None and rg not in groups:
                return pf.schema_arrow.empty_table() if projection is None \
                    else pf.schema_arrow.empty_table().select(projection)
            return pf.read_row_groups([rg], columns=projection)
        except (SnapshotChanged, StorageError):
            raise  # already typed (mutation / retries spent) — never corrupt
        except MemoryError as ex:
            # transient pressure (pa.ArrowMemoryError subclasses this), not
            # bad bytes: quarantining would brick the row group for the
            # process lifetime — surface per-query instead
            raise ConnectorError(
                f"parquet partition {index} read failed for {self.path}: "
                f"{ex}") from None
        except Exception as ex:
            # the store served the pinned bytes and they did not parse:
            # corruption, fatal for THIS (file, row group) — quarantined
            raise quarantine.record(path, fh.etag, rg, str(ex),
                                    table=self.path) from None

    def _read_file(self, path: str, projection, filters) -> pa.Table:
        fh = self._open(path)
        quarantine.check(path, fh.etag, -1, table=self.path)
        try:
            pf = pq.ParquetFile(fh)
            groups = _prune_row_groups(pf, filters)
            if groups is None:
                t = pf.read(columns=projection)
            else:
                t = pf.read_row_groups(groups, columns=projection)
            return t
        except (SnapshotChanged, StorageError):
            raise
        except MemoryError as ex:   # transient pressure, never quarantined
            raise ConnectorError(
                f"parquet read failed for {path}: {ex}") from None
        except Exception as ex:
            raise quarantine.record(path, fh.etag, -1, str(ex),
                                    table=self.path) from None


def files_bytes(files: list[str]) -> Optional[int]:
    """Total on-disk size of a connector's files (chunked-execution sizing)."""
    try:
        return sum(os.path.getsize(f) for f in files)
    except OSError:
        return None


def file_snapshot(files: list[str]) -> tuple:
    """(path, mtime_ns, size) per file — the cache/CDC invalidation token for
    file-backed connectors (igloo_tpu/exec/cache.py, igloo_tpu/cdc.py)."""
    out = []
    for f in files:
        try:
            st = os.stat(f)
            out.append((f, st.st_mtime_ns, st.st_size))
        except OSError:
            out.append((f, -1, -1))
    return tuple(out)


def _expand(path: str) -> list[str]:
    if os.path.isdir(path):
        return sorted(_glob.glob(os.path.join(path, "**", "*.parquet"),
                                 recursive=True))
    if any(ch in path for ch in "*?["):
        return sorted(_glob.glob(path))
    return [path] if os.path.exists(path) else []


def _expand_store(store, path: str, suffix: str = ".parquet") -> list[str]:
    """File set for `path` on any ObjectStore backend: a plain key lists
    itself, a glob matches, a prefix/directory lists recursively filtered
    to `suffix` (the LocalStore case reproduces `_expand` exactly)."""
    keys = store.list_prefix(path)
    if keys == [path] or any(ch in path for ch in "*?["):
        return sorted(keys)   # plain key or explicit glob: take as matched
    return sorted(k for k in keys if k.endswith(suffix))


def _prune_row_groups(pf: pq.ParquetFile, filters) -> Optional[list[int]]:
    """Row-group pruning from column statistics for simple `col <op> literal`
    predicates. Best-effort: returning None means read everything (the engine
    re-applies every filter exactly)."""
    if not filters:
        return None
    preds = []
    for f in filters:
        p = _simple_pred(f)
        if p is not None:
            preds.append(p)
    if not preds:
        return None
    meta = pf.metadata
    name_to_idx = {meta.schema.column(i).path: i
                   for i in range(meta.num_columns)}
    keep = []
    for g in range(meta.num_row_groups):
        rg = meta.row_group(g)
        alive = True
        for col, op, val in preds:
            ci = name_to_idx.get(col)
            if ci is None:
                continue
            st = rg.column(ci).statistics
            if st is None or not st.has_min_max:
                continue
            mn, mx = _stat_value(st.min), _stat_value(st.max)
            if mn is None or mx is None:
                continue
            try:
                if op == ">" and mx <= val:
                    alive = False
                elif op == ">=" and mx < val:
                    alive = False
                elif op == "<" and mn >= val:
                    alive = False
                elif op == "<=" and mn > val:
                    alive = False
                elif op == "=" and (val < mn or val > mx):
                    alive = False
            except TypeError:
                continue
            if not alive:
                break
        if alive:
            keep.append(g)
    if len(keep) == meta.num_row_groups:
        return None
    return keep


_OPS = {E.BinOp.GT: ">", E.BinOp.GTE: ">=", E.BinOp.LT: "<", E.BinOp.LTE: "<=",
        E.BinOp.EQ: "="}
_FLIP = {">": "<", ">=": "<=", "<": ">", "<=": ">=", "=": "="}


def _simple_pred(e: E.Expr):
    """col <op> literal (either order) -> (col_name, op, python_value)."""
    if not isinstance(e, E.Binary) or e.op not in _OPS:
        return None
    l, r = e.left, e.right
    if isinstance(l, E.Column) and isinstance(r, E.Literal):
        col, lit, op = l, r, _OPS[e.op]
    elif isinstance(r, E.Column) and isinstance(l, E.Literal):
        col, lit, op = r, l, _FLIP[_OPS[e.op]]
    else:
        return None
    v = lit.value
    if v is None:
        return None
    if lit.literal_type is not None and lit.literal_type.id.value == "date32":
        v = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(v))
    return (col.name.split(".")[-1], op, v)


def _stat_value(v):
    if isinstance(v, bytes):
        try:
            return v.decode("utf-8")
        except UnicodeDecodeError:
            return None
    return v
