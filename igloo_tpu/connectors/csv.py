"""CSV connector.

Counterpart of the reference's filesystem connector (crates/connectors/filesystem/
src/lib.rs:12-46), which reads a whole CSV into Vec<Vec<String>> under its own
private TableProvider trait, disconnected from the engine. Ours implements the
ENGINE's provider protocol (typed arrow decode via pyarrow's C++ CSV reader, and
the coordinator's ListingTable fixture use-case, coordinator/src/main.rs:26-45).
"""
from __future__ import annotations

import glob as _glob
import os
from typing import Optional

import pyarrow as pa
import pyarrow.csv as pacsv

from igloo_tpu.errors import ConnectorError
from igloo_tpu.exec.batch import schema_from_arrow
from igloo_tpu.types import Schema


class CsvTable:
    stable_row_order = True  # deterministic file order + sequential parse
    bytes_expansion = 1.5    # text numbers re-encode to comparable lane bytes

    def __deepcopy__(self, memo):
        # providers are shared by plan/expression copies (see copy_plan)
        return self

    def __init__(self, path: str, has_header: bool = True,
                 delimiter: str = ","):
        self.path = path
        self.has_header = has_header
        self.delimiter = delimiter
        self._files = _expand(path)
        if not self._files:
            raise ConnectorError(f"no csv files at {path}")
        self._schema_arrow = self._read_file(self._files[0]).schema
        self._schema = schema_from_arrow(self._schema_arrow)

    def _read_opts(self):
        if self.has_header:
            ropts = pacsv.ReadOptions()
        else:
            # peek at first line for column count
            with open(self._files[0], "r", encoding="utf-8") as fh:
                first = fh.readline()
            n = len(first.rstrip("\n").split(self.delimiter))
            ropts = pacsv.ReadOptions(
                column_names=[f"column_{i + 1}" for i in range(n)])
        return ropts

    def _read_file(self, path: str) -> pa.Table:
        try:
            return pacsv.read_csv(
                path, read_options=self._read_opts(),
                parse_options=pacsv.ParseOptions(delimiter=self.delimiter))
        except FileNotFoundError:
            raise ConnectorError(f"csv file not found: {path}") from None
        except pa.ArrowInvalid as ex:
            raise ConnectorError(f"csv parse failed for {path}: {ex}") from None

    def snapshot(self):
        from igloo_tpu.connectors.parquet import file_snapshot
        return file_snapshot(self._files)

    def schema(self) -> Schema:
        return self._schema

    def estimated_bytes(self):
        from igloo_tpu.connectors.parquet import files_bytes
        return files_bytes(self._files)

    def num_partitions(self) -> int:
        return len(self._files)

    def read(self, projection: Optional[list[str]] = None,
             filters: Optional[list] = None) -> pa.Table:
        tables = [self._read_file(f) for f in self._files]
        t = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
        if projection is not None:
            t = t.select(projection)
        return t

    def read_partition(self, index: int, projection=None, filters=None):
        t = self._read_file(self._files[index])
        if projection is not None:
            t = t.select(projection)
        return t


def _expand(path: str) -> list[str]:
    if os.path.isdir(path):
        return sorted(_glob.glob(os.path.join(path, "**", "*.csv"),
                                 recursive=True))
    if any(ch in path for ch in "*?["):
        return sorted(_glob.glob(path))
    return [path] if os.path.exists(path) else []
