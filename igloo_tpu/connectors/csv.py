"""CSV connector.

Counterpart of the reference's filesystem connector (crates/connectors/filesystem/
src/lib.rs:12-46), which reads a whole CSV into Vec<Vec<String>> under its own
private TableProvider trait, disconnected from the engine. Ours implements the
ENGINE's provider protocol (typed arrow decode via pyarrow's C++ CSV reader, and
the coordinator's ListingTable fixture use-case, coordinator/src/main.rs:26-45).

Reads route through the object-store layer (igloo_tpu/storage): policy-
retried verified reads, pinned snapshot etags (mid-query mutation raises a
typed `SnapshotChanged`), and a vanished file mapped to a snapshot change
instead of a raw FileNotFoundError — same contract as the parquet
connector (docs/storage.md).
"""
from __future__ import annotations

from typing import Optional

import pyarrow as pa
import pyarrow.csv as pacsv

from igloo_tpu.errors import ConnectorError, SnapshotChanged, StorageError
from igloo_tpu.exec.batch import schema_from_arrow
from igloo_tpu.storage import local_store
from igloo_tpu.storage import snapshot as _snapshot
from igloo_tpu.types import Schema


class CsvTable:
    stable_row_order = True  # deterministic file order + sequential parse
    bytes_expansion = 1.5    # text numbers re-encode to comparable lane bytes

    def __deepcopy__(self, memo):
        # providers are shared by plan/expression copies (see copy_plan)
        return self

    def __init__(self, path: str, has_header: bool = True,
                 delimiter: str = ",", store=None):
        self.path = path
        self.has_header = has_header
        self.delimiter = delimiter
        self._store = store if store is not None else local_store()
        from igloo_tpu.connectors.parquet import _expand_store
        self._files = _expand_store(self._store, path, suffix=".csv")
        if not self._files:
            raise ConnectorError(f"no csv files at {path}")
        self._schema_arrow = self._read_file(self._files[0]).schema
        self._schema = schema_from_arrow(self._schema_arrow)

    def _read_opts(self):
        if self.has_header:
            ropts = pacsv.ReadOptions()
        else:
            # peek at the head for the column count (one small ranged read)
            head = self._store.get_range(self._files[0], 0, 65536)
            first = head.decode("utf-8", "replace").split("\n", 1)[0]
            n = len(first.rstrip("\r\n").split(self.delimiter))
            ropts = pacsv.ReadOptions(
                column_names=[f"column_{i + 1}" for i in range(n)])
        return ropts

    def _open(self, path: str):
        pins = _snapshot.pinned_etags(self)
        want = pins.get(path) if pins is not None else None
        try:
            return self._store.open_input(path, want_etag=want,
                                          table=self.path)
        except FileNotFoundError:
            raise SnapshotChanged(
                f"csv file vanished: {path} (table {self.path})",
                table=self.path, key=path) from None

    def _read_file(self, path: str) -> pa.Table:
        try:
            return pacsv.read_csv(
                self._open(path), read_options=self._read_opts(),
                parse_options=pacsv.ParseOptions(delimiter=self.delimiter))
        except (SnapshotChanged, StorageError):
            raise
        except pa.ArrowInvalid as ex:
            raise ConnectorError(f"csv parse failed for {path}: {ex}") from None

    def snapshot(self):
        tok, _etags = _snapshot.pin(self, self._snapshot_now)
        return tok

    def _snapshot_now(self) -> tuple:
        return self._store.snapshot_token(self._files)

    def schema(self) -> Schema:
        return self._schema

    def estimated_bytes(self):
        return self._store.files_bytes(self._files)

    def num_partitions(self) -> int:
        return len(self._files)

    def read(self, projection: Optional[list[str]] = None,
             filters: Optional[list] = None) -> pa.Table:
        tables = [self._read_file(f) for f in self._files]
        t = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
        if projection is not None:
            t = t.select(projection)
        return t

    def read_partition(self, index: int, projection=None, filters=None):
        t = self._read_file(self._files[index])
        if projection is not None:
            t = t.select(projection)
        return t
