"""Iceberg connector with REAL metadata handling.

The reference's Iceberg scan ignores the table's metadata entirely and globs
`{table}/data/**/*.parquet` (crates/connectors/iceberg/src/lib.rs:42-76; its own
module doc calls this a "basic implementation"). This one follows the Iceberg v1/v2
spec: version-hint -> vN.metadata.json -> current snapshot -> manifest list (Avro)
-> manifests (Avro) -> live data-file entries, honoring delete/existing status and
snapshot selection — falling back to the reference's glob behavior only when no
metadata exists (with a warning).
"""
from __future__ import annotations

import glob as _glob
import json
import logging
import os
import re
from typing import Optional
from urllib.parse import urlparse

import pyarrow as pa
import pyarrow.parquet as pq

from igloo_tpu.connectors.avro import read_avro_file
from igloo_tpu.connectors.parquet import _prune_row_groups
from igloo_tpu.errors import ConnectorError, SnapshotChanged, StorageError
from igloo_tpu.exec.batch import schema_from_arrow
from igloo_tpu.storage import local_store, quarantine
from igloo_tpu.storage import snapshot as _snapshot
from igloo_tpu.types import Schema

log = logging.getLogger("igloo_tpu.iceberg")

# manifest entry / data file status codes (iceberg spec)
_STATUS_DELETED = 2
_CONTENT_DATA = 0


class IcebergTable:
    stable_row_order = True  # manifest-ordered data files, deterministic
    bytes_expansion = 3.5    # parquet data files, as ParquetTable

    def __deepcopy__(self, memo):
        # providers are shared by plan/expression copies (see copy_plan)
        return self

    def __init__(self, path: str, snapshot_id: Optional[int] = None,
                 store=None):
        self.path = path.rstrip("/")
        self.snapshot_id = snapshot_id
        # data-file reads route through the object store (docs/storage.md);
        # metadata (version JSON, Avro manifests) stays on the local
        # filesystem — iceberg commits re-WRITE metadata versions, so the
        # etag-pinned window is the data files the chosen snapshot names
        self._store = store if store is not None else local_store()
        self._files = self._resolve_data_files()
        if not self._files:
            raise ConnectorError(
                f"iceberg table at {path} has no data files")
        self._arrow_schema = pq.read_schema(
            self._store.open_input(self._files[0], table=self.path))
        self._schema = schema_from_arrow(self._arrow_schema)

    # --- metadata resolution ---

    def _metadata_file(self) -> Optional[str]:
        mdir = os.path.join(self.path, "metadata")
        hint = os.path.join(mdir, "version-hint.text")
        if os.path.exists(hint):
            with open(hint) as fh:
                v = fh.read().strip()
            for pattern in (f"v{v}.metadata.json", f"{v}.metadata.json"):
                cand = os.path.join(mdir, pattern)
                if os.path.exists(cand):
                    return cand
        # no/stale hint: take the highest vN.metadata.json present
        cands = _glob.glob(os.path.join(mdir, "*.metadata.json"))
        if not cands:
            return None

        def version_of(p):
            m = re.search(r"v?(\d+)[.-]", os.path.basename(p))
            return int(m.group(1)) if m else -1
        return max(cands, key=version_of)

    def _resolve_data_files(self) -> list[str]:
        meta_path = self._metadata_file()
        if meta_path is None:
            # reference-compatible fallback (its only behavior): glob data/
            log.warning("iceberg: no metadata at %s, falling back to glob",
                        self.path)
            return sorted(_glob.glob(
                os.path.join(self.path, "data", "**", "*.parquet"),
                recursive=True))
        with open(meta_path) as fh:
            meta = json.load(fh)
        snap = self._pick_snapshot(meta)
        if snap is None:
            return []
        files: list[str] = []
        if "manifest-list" in snap:
            mlist = self._localize(snap["manifest-list"])
            for m in read_avro_file(mlist):
                mp = m.get("manifest_path")
                if mp is None:
                    continue
                files.extend(self._read_manifest(self._localize(mp)))
        else:  # v1 inline manifests list
            for mp in snap.get("manifests", []):
                files.extend(self._read_manifest(self._localize(mp)))
        return files

    def _pick_snapshot(self, meta: dict) -> Optional[dict]:
        snaps = meta.get("snapshots", [])
        if not snaps:
            return None
        want = self.snapshot_id
        if want is None:
            want = meta.get("current-snapshot-id")
        for s in snaps:
            if s.get("snapshot-id") == want:
                return s
        if self.snapshot_id is not None:
            raise ConnectorError(
                f"iceberg: snapshot {self.snapshot_id} not found")
        return snaps[-1]

    def _read_manifest(self, path: str) -> list[str]:
        out = []
        for entry in read_avro_file(path):
            if entry.get("status") == _STATUS_DELETED:
                continue
            df = entry.get("data_file", {})
            if df.get("content", _CONTENT_DATA) != _CONTENT_DATA:
                continue  # delete files (v2) are not scan inputs
            fp = df.get("file_path")
            if fp:
                out.append(self._localize(fp))
        return out

    def _localize(self, uri: str) -> str:
        """Map a metadata URI to a local path; relative paths resolve against
        the table root."""
        parsed = urlparse(uri)
        if parsed.scheme in ("file", ""):
            p = parsed.path if parsed.scheme == "file" else uri
            if os.path.isabs(p) and os.path.exists(p):
                return p
            # re-root: find the table-relative suffix
            for marker in ("/metadata/", "/data/"):
                if marker in p:
                    return self.path + p[p.rindex(marker):]
            return os.path.join(self.path, p)
        raise ConnectorError(f"iceberg: unsupported URI scheme {parsed.scheme}")

    # --- provider protocol ---

    def snapshot(self):
        """Iceberg snapshot token: metadata file + data files (store etags).
        A new table commit writes a new metadata version, changing the
        token; _refresh() here AND in read()/read_partition() keeps the served
        file list consistent with the version the token is computed from.
        Inside a query's pinned scope the first call pins token + per-file
        etags (storage/snapshot.py) — the whole query reads ONE commit."""
        tok, _etags = _snapshot.pin(self, self._snapshot_now)
        return tok

    def _snapshot_now(self) -> tuple:
        self._refresh()
        meta = self._metadata_file()
        return self._store.snapshot_token(
            ([meta] if meta else []) + self._files)

    def _refresh(self) -> None:
        """Re-resolve data files when the table's metadata version moved (a
        commit happened after __init__); keeps read() consistent with
        snapshot()-driven cache invalidation."""
        files = self._resolve_data_files()
        if files and files != self._files:
            self._files = files

    def schema(self) -> Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self._files)

    def _maybe_refresh(self) -> None:
        # inside a pinned query scope the file list is already the one the
        # pinned snapshot resolved — re-resolving mid-query would let a
        # concurrent commit swap in files the pin never covered
        if _snapshot.pinned_etags(self) is None:
            self._refresh()

    def read(self, projection: Optional[list[str]] = None,
             filters: Optional[list] = None) -> pa.Table:
        self._maybe_refresh()
        tables = [self._read_file(f, projection, filters) for f in self._files]
        return pa.concat_tables(tables) if len(tables) > 1 else tables[0]

    def read_partition(self, index, projection=None, filters=None) -> pa.Table:
        self._maybe_refresh()
        return self._read_file(self._files[index], projection, filters)

    def _read_file(self, path, projection, filters) -> pa.Table:
        pins = _snapshot.pinned_etags(self)
        want = pins.get(path) if pins is not None else None
        try:
            fh = self._store.open_input(path, want_etag=want,
                                        table=self.path)
        except FileNotFoundError:
            # an expired/compacted data file: a commit happened — the typed
            # snapshot change the engine converts into one re-plan
            raise SnapshotChanged(
                f"iceberg data file vanished: {path} (table {self.path})",
                table=self.path, key=path) from None
        quarantine.check(path, fh.etag, -1, table=self.path)
        try:
            pf = pq.ParquetFile(fh)
            groups = _prune_row_groups(pf, filters)
            if groups is None:
                return pf.read(columns=projection)
            return pf.read_row_groups(groups, columns=projection)
        except (SnapshotChanged, StorageError):
            raise
        except MemoryError as ex:   # transient pressure, never quarantined
            raise ConnectorError(
                f"iceberg parquet read failed for {path}: {ex}") from None
        except Exception as ex:
            raise quarantine.record(path, fh.etag, -1, str(ex),
                                    table=self.path) from None
