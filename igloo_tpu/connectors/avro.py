"""Minimal Apache Avro container-file reader (read-only, schema-driven).

Iceberg manifest lists and manifest files are Avro; no Avro library is available
in this environment, so this implements the subset of the Avro 1.x spec those
files use: the object container format (magic `Obj\\x01`, metadata map with
embedded writer schema JSON, sync-marker-delimited blocks; null/deflate codecs)
and the binary encoding for records, unions, arrays, maps, and primitives.

This is what lets the Iceberg connector read REAL table metadata instead of
globbing for parquet like the reference does (crates/connectors/iceberg/src/
lib.rs:42-76, module doc: "basic implementation").
"""
from __future__ import annotations

import io
import json
import struct
import zlib

from igloo_tpu.errors import ConnectorError

MAGIC = b"Obj\x01"


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ConnectorError("avro: truncated data")
        out = self.buf[self.pos: self.pos + n]
        self.pos += n
        return out

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)

    # --- primitives (avro binary encoding) ---

    def zigzag_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.read(1)[0]
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
            if shift > 70:
                raise ConnectorError("avro: varint too long")
        return (acc >> 1) ^ -(acc & 1)

    def a_null(self, schema=None):
        return None

    def a_boolean(self, schema=None):
        return self.read(1) != b"\x00"

    def a_int(self, schema=None):
        return self.zigzag_long()

    a_long = a_int

    def a_float(self, schema=None):
        return struct.unpack("<f", self.read(4))[0]

    def a_double(self, schema=None):
        return struct.unpack("<d", self.read(8))[0]

    def a_bytes(self, schema=None):
        n = self.zigzag_long()
        return self.read(n)

    def a_string(self, schema=None):
        return self.a_bytes().decode("utf-8")

    def a_fixed(self, schema):
        return self.read(schema["size"])

    def a_enum(self, schema):
        idx = self.zigzag_long()
        return schema["symbols"][idx]

    # --- compound ---

    def decode(self, schema, named: dict):
        if isinstance(schema, str):
            if schema in named:
                return self.decode(named[schema], named)
            m = getattr(self, "a_" + schema, None)
            if m is None:
                raise ConnectorError(f"avro: unknown type {schema!r}")
            return m()
        if isinstance(schema, list):  # union
            idx = self.zigzag_long()
            if not (0 <= idx < len(schema)):
                raise ConnectorError("avro: bad union branch")
            return self.decode(schema[idx], named)
        t = schema["type"]
        if t == "record":
            out = {}
            for f in schema["fields"]:
                out[f["name"]] = self.decode(f["type"], named)
            return out
        if t == "array":
            out = []
            while True:
                n = self.zigzag_long()
                if n == 0:
                    break
                if n < 0:
                    self.zigzag_long()  # block byte size, unused
                    n = -n
                for _ in range(n):
                    out.append(self.decode(schema["items"], named))
            return out
        if t == "map":
            out = {}
            while True:
                n = self.zigzag_long()
                if n == 0:
                    break
                if n < 0:
                    self.zigzag_long()
                    n = -n
                for _ in range(n):
                    k = self.a_string()
                    out[k] = self.decode(schema["values"], named)
            return out
        if t == "fixed":
            return self.a_fixed(schema)
        if t == "enum":
            return self.a_enum(schema)
        # logical types / aliased primitives fall through to base type
        m = getattr(self, "a_" + t, None)
        if m is None:
            raise ConnectorError(f"avro: unknown complex type {t!r}")
        return m(schema)


def _collect_named(schema, named: dict):
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "fixed", "enum") and "name" in schema:
            named[schema["name"]] = schema
        if t == "record":
            for f in schema.get("fields", []):
                _collect_named(f["type"], named)
        elif t == "array":
            _collect_named(schema.get("items"), named)
        elif t == "map":
            _collect_named(schema.get("values"), named)
    elif isinstance(schema, list):
        for s in schema:
            _collect_named(s, named)


def read_avro_file(path: str) -> list[dict]:
    """Read all records of an Avro object container file as dicts."""
    with open(path, "rb") as fh:
        data = fh.read()
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ConnectorError(f"not an avro file: {path}")
    meta = {}
    while True:
        n = r.zigzag_long()
        if n == 0:
            break
        if n < 0:
            r.zigzag_long()
            n = -n
        for _ in range(n):
            k = r.a_string()
            meta[k] = r.a_bytes()
    sync = r.read(16)
    schema = json.loads(meta[b"avro.schema".decode()]
                        if isinstance(meta.get("avro.schema"), str)
                        else meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode() \
        if isinstance(meta.get("avro.codec", b"null"), bytes) else "null"
    named: dict = {}
    _collect_named(schema, named)
    records = []
    while not r.at_end():
        count = r.zigzag_long()
        nbytes = r.zigzag_long()
        block = r.read(nbytes)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ConnectorError(f"avro codec {codec!r} not supported")
        br = _Reader(block)
        for _ in range(count):
            records.append(br.decode(schema, named))
        if r.read(16) != sync:
            raise ConnectorError(f"avro: bad sync marker in {path}")
    return records
