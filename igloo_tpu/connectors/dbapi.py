"""Federated SQL-database connectors (Postgres / MySQL / any DBAPI source).

The reference declares postgres and mysql connector crates that are empty stubs
(crates/connectors/postgres/src/lib.rs:1, mysql same — SURVEY.md #24/#25); per the
build mandate we implement the declared capability: a federation connector that
pushes projection + simple predicates down as remote SQL, fetches rows through a
DBAPI driver, and converts to Arrow for the device path. Drivers are not bundled
in this environment, so Postgres/MySQL classes raise a clear error without one —
the shared DBAPI core is exercised against sqlite3 in the tests.
"""
from __future__ import annotations

import datetime as _dt
from typing import Callable, Optional

import pyarrow as pa

from igloo_tpu.errors import ConnectorError
from igloo_tpu.exec.batch import schema_from_arrow
from igloo_tpu.plan import expr as E
from igloo_tpu.types import Schema

_OPS = {E.BinOp.GT: ">", E.BinOp.GTE: ">=", E.BinOp.LT: "<", E.BinOp.LTE: "<=",
        E.BinOp.EQ: "=", E.BinOp.NEQ: "<>"}


def _render_pushdown(filters, quote: str = '"') -> str:
    """Render simple `col <op> literal` conjuncts as a remote WHERE clause in
    the target dialect's identifier quoting (backticks for MySQL). Anything
    unrenderable is skipped — the engine re-applies all filters."""
    parts = []
    for f in filters or []:
        if not (isinstance(f, E.Binary) and f.op in _OPS):
            continue
        l, r = f.left, f.right
        if isinstance(l, E.Column) and isinstance(r, E.Literal):
            col, lit, op = l, r, _OPS[f.op]
        elif isinstance(r, E.Column) and isinstance(l, E.Literal):
            col, lit, op = r, l, {">": "<", ">=": "<=", "<": ">", "<=": ">=",
                                  "=": "=", "<>": "<>"}[_OPS[f.op]]
        else:
            continue
        v = lit.value
        if v is None:
            continue
        if lit.literal_type is not None and lit.literal_type.id.value == "date32":
            d = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(v))
            rendered = f"'{d.isoformat()}'"
        elif isinstance(v, str):
            rendered = "'" + v.replace("'", "''") + "'"
        elif isinstance(v, bool):
            rendered = "TRUE" if v else "FALSE"
        else:
            rendered = repr(v)
        name = col.name.split(".")[-1]
        parts.append(f'{quote}{name}{quote} {op} {rendered}')
    return " AND ".join(parts)


class DbApiTable:
    """A remote table reachable through a DBAPI connection factory."""

    # a SELECT with no ORDER BY may return rows in any order, so separate
    # reads cannot be stitched column-wise (executor falls back to the
    # whole-batch scan cache)
    stable_row_order = False

    def __init__(self, connect: Callable, table: str,
                 quote: str = '"'):
        self._connect = connect
        self.table = table
        self.quote = quote
        self._schema_arrow = self._probe_schema()
        self._schema = schema_from_arrow(self._schema_arrow)

    def _q(self, ident: str) -> str:
        return f"{self.quote}{ident}{self.quote}"

    def _probe_schema(self) -> pa.Schema:
        t = self._fetch(f"SELECT * FROM {self._q(self.table)} LIMIT 1")
        return t.schema

    def _fetch(self, sql: str) -> pa.Table:
        try:
            conn = self._connect()
        except Exception as ex:
            raise ConnectorError(
                f"cannot connect to remote database: {ex}") from None
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        except Exception as ex:
            raise ConnectorError(f"remote query failed: {ex}") from None
        finally:
            conn.close()
        if rows:
            arrays = [pa.array([r[i] for r in rows]) for i in range(len(cols))]
        else:
            arrays = [pa.array([], type=pa.string()) for _ in cols]
        return pa.Table.from_arrays(arrays, names=cols)

    # --- provider protocol ---

    def schema(self) -> Schema:
        return self._schema

    def num_partitions(self) -> int:
        return 1

    def read(self, projection: Optional[list[str]] = None,
             filters: Optional[list] = None) -> pa.Table:
        cols = "*" if projection is None else \
            ", ".join(self._q(c) for c in projection)
        sql = f"SELECT {cols} FROM {self._q(self.table)}"
        where = _render_pushdown(filters, self.quote)
        if where:
            sql += f" WHERE {where}"
        t = self._fetch(sql)
        if t.num_rows == 0 and projection is not None:
            # retype empty result from the probed schema
            arrays = [pa.array([], type=self._schema_arrow.field(c).type)
                      for c in projection]
            t = pa.Table.from_arrays(arrays, names=list(projection))
        return t

    def read_partition(self, index: int, projection=None, filters=None):
        return self.read(projection, filters)


class PostgresTable(DbApiTable):
    """Postgres federation source (reference crates/connectors/postgres, stub).

    Uses psycopg2 when installed; otherwise falls back to the bundled
    pure-Python wire-protocol client (connectors/pgwire.py — protocol v3,
    simple query, trust/cleartext auth), so federation works without binary
    drivers."""

    def __init__(self, dsn: str, table: str):
        try:
            import psycopg2  # type: ignore
            connect = lambda: psycopg2.connect(dsn)  # noqa: E731
        except ImportError:
            from igloo_tpu.connectors import pgwire
            connect = lambda: pgwire.connect(dsn)  # noqa: E731
        super().__init__(connect, table, quote='"')


class MySqlTable(DbApiTable):
    """MySQL federation source (reference crates/connectors/mysql, stub)."""

    def __init__(self, table: str, **conn_kwargs):
        try:
            import pymysql  # type: ignore
        except ImportError:
            raise ConnectorError(
                "mysql connector requires pymysql (not bundled in this "
                "environment); install it or use DbApiTable with your own "
                "driver") from None
        super().__init__(lambda: pymysql.connect(**conn_kwargs), table,
                         quote="`")
