"""The coordinator speaks plain Arrow Flight: any stock client works — no
igloo_tpu import needed on the client side.

    python examples/flight_client.py grpc+tcp://127.0.0.1:50051 "SELECT 1 AS x"
"""
import sys

import pyarrow.flight as flight


def main(addr: str, sql: str):
    client = flight.connect(addr)
    # schema without executing
    info = client.get_flight_info(flight.FlightDescriptor.for_command(sql.encode()))
    print("schema:", info.schema)
    # execute: the ticket IS the SQL
    table = client.do_get(flight.Ticket(sql.encode())).read_all()
    print(table.to_pandas().to_string(index=False))


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "grpc+tcp://127.0.0.1:50051",
         sys.argv[2] if len(sys.argv) > 2 else "SELECT 1 AS x")
