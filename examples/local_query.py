"""Minimal local session: register a Parquet file, run SQL on the default
device (TPU when visible, else CPU).

    python examples/local_query.py
"""
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import igloo_tpu


def main():
    rng = np.random.default_rng(0)
    n = 100_000
    pq.write_table(pa.table({
        "region": pa.array([f"r{i % 5}" for i in range(n)]),
        "amount": np.round(rng.random(n) * 100, 2),
        "qty": rng.integers(1, 20, n),
    }), "/tmp/sales.parquet")

    sess = igloo_tpu.connect()
    sess.register_parquet("sales", "/tmp/sales.parquet")
    out = sess.sql("""
        SELECT region, count(*) AS orders, sum(amount * qty) AS revenue
        FROM sales GROUP BY region ORDER BY revenue DESC
    """)
    print(out.to_pandas().to_string(index=False))


if __name__ == "__main__":
    main()
