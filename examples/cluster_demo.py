"""Distributed execution demo: coordinator + two workers in one process,
a client running SQL over Arrow Flight, per-fragment metrics.

    python examples/cluster_demo.py
"""
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from igloo_tpu.cluster.client import DistributedClient
from igloo_tpu.cluster.coordinator import CoordinatorServer
from igloo_tpu.cluster.worker import Worker
from igloo_tpu.connectors.parquet import ParquetTable


def main():
    rng = np.random.default_rng(0)
    n = 200_000
    pq.write_table(pa.table({
        "k": rng.integers(0, 100, n),
        "v": rng.random(n),
    }), "/tmp/big.parquet", row_group_size=20_000)

    coord = CoordinatorServer("grpc+tcp://127.0.0.1:0")
    addr = f"127.0.0.1:{coord.port}"
    workers = [Worker(addr, port=0) for _ in range(2)]
    for w in workers:
        w.start()
    time.sleep(0.3)

    coord.register_table("big", ParquetTable("/tmp/big.parquet"))
    client = DistributedClient(addr)
    print("cluster:", client.cluster_status())
    out = client.execute(
        "SELECT k % 10 AS bucket, count(*) AS c, sum(v) AS s "
        "FROM big GROUP BY k % 10 ORDER BY bucket")
    print(out.to_pandas().to_string(index=False))
    m = client.last_metrics()
    print(f"{len(m['fragments'])} fragments over "
          f"{len({f['worker'] for f in m['fragments']})} workers in "
          f"{m['execution_time_s']:.3f}s")

    client.close()
    for w in workers:
        w.shutdown()
    coord.shutdown()


if __name__ == "__main__":
    main()
